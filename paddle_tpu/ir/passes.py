"""Builtin passes — the TPU-relevant core of the reference's 268-file
fluid/framework/ir pass library.

Kept deliberately small: on TPU, XLA owns fusion/layout/scheduling, so the
passes that still pay are the PROGRAM-level ones XLA can't see across the
trace boundary — constant folding (pre-computing frozen subgraphs, which
subsumes most of conv_bn_fuse's arithmetic once BN runs in eval mode),
algebraic identity cleanup, CSE and DCE (native, ir_core.cc), and
inference-only rewrites (dropout elimination). Pattern passes use simple
def-use matching — the GraphPatternDetector analog over Value.defining_op().
"""

from __future__ import annotations

import logging

import numpy as np

from ..observability import metrics as _metrics
from .core import CONSTANT_OP, Program
from .pass_manager import Pass, register_pass

_log = logging.getLogger(__name__)

_FOLD_ELEMENT_LIMIT = 1 << 22  # don't materialize folded constants > 4M elems


@register_pass
class DeadCodeEliminationPass(Pass):
    """Native reverse-sweep DCE (framework/ir delete_op_device_pass family)."""

    name = "dce"

    def run(self, program: Program) -> int:
        return program.dce()


@register_pass
class CommonSubexpressionEliminationPass(Pass):
    """Native structural CSE over (name, operands, attrs, result types)."""

    name = "cse"

    def run(self, program: Program) -> int:
        return program.cse()


def _const_value(program: Program, v):
    op = v.defining_op()
    if op is None or op.name != CONSTANT_OP:
        return None
    return program.const_vals.get(op.id)


@register_pass
class ConstantFoldingPass(Pass):
    """Evaluate side-effect-free ops whose operands are all constants
    (constant_folding_pass.cc analog). Evaluation re-binds the primitive on
    the concrete values — i.e. runs it eagerly through XLA once, at
    optimization time instead of every execution."""

    name = "constant_folding"

    def run(self, program: Program) -> int:
        changed = 0
        for op in program.ops():
            if op.name == CONSTANT_OP or op.has_side_effect:
                continue
            if op.id not in program.op_bind:
                continue
            vals = []
            all_const = True
            for operand in op.operands:
                cv = _const_value(program, operand)
                if cv is None:
                    all_const = False
                    break
                vals.append(cv)
            if not all_const:
                continue
            out_elems = sum(int(np.prod(r.type.shape or (1,))) for r in op.results)
            if out_elems > _FOLD_ELEMENT_LIMIT:
                continue
            prim, params = program.op_bind[op.id]
            try:
                subfuns, bind_params = prim.get_bind_params(params)
                folded = prim.bind(*subfuns, *vals, **bind_params)
            except Exception:
                continue  # unfoldable (needs trace context) — leave as-is
            if not prim.multiple_results:
                folded = [folded]
            for res, fv in zip(op.results, folded):
                # insert at the folded op's slot: its users come later, so
                # def-before-use survives (appending at program end would not)
                res.replace_all_uses_with(
                    program.add_constant(np.asarray(fv), before=op).result(0))
            op.erase()  # now dead; erasing here keeps re-runs convergent
            changed += 1
        return changed


def _is_const_filled(program: Program, v, scalar) -> bool:
    cv = _const_value(program, v)
    if cv is None:
        return False
    try:
        return bool(np.all(np.asarray(cv) == scalar))
    except Exception:
        return False


@register_pass
class AlgebraicSimplifyPass(Pass):
    """Identity cleanup: x+0, x-0, x*1, x/1, double-transpose, no-op convert
    (the simplify_* / identity_op_clean passes of framework/ir)."""

    name = "algebraic_simplify"

    def run(self, program: Program) -> int:
        changed = 0
        for op in program.ops():
            name = op.name
            repl = None
            if name in ("pd.add", "pd.sub") and len(op.operands) == 2:
                a, b = op.operands
                if _is_const_filled(program, b, 0) and b.type == a.type == op.result(0).type:
                    repl = a
                elif name == "pd.add" and _is_const_filled(program, a, 0) \
                        and a.type == b.type == op.result(0).type:
                    repl = b
            elif name in ("pd.mul", "pd.div") and len(op.operands) == 2:
                a, b = op.operands
                if _is_const_filled(program, b, 1) and b.type == a.type == op.result(0).type:
                    repl = a
                elif name == "pd.mul" and _is_const_filled(program, a, 1) \
                        and a.type == b.type == op.result(0).type:
                    repl = b
            elif name == "pd.transpose":
                inner = op.operands[0].defining_op()
                if inner is not None and inner.name == "pd.transpose":
                    outer_p = op.attrs().get("permutation")
                    inner_p = inner.attrs().get("permutation")
                    if outer_p and inner_p and \
                            [inner_p[p] for p in outer_p] == list(range(len(outer_p))):
                        repl = inner.operands[0]
            elif name == "pd.convert_element_type":
                if op.result(0).type == op.operands[0].type:
                    repl = op.operands[0]
            if repl is not None:
                n = op.result(0).replace_all_uses_with(repl)
                erased = op.erase()
                if n or erased:  # count real rewrites only, or convergence
                    changed += 1  # detection never settles
        return changed


@register_pass
class DeleteQuantDequantPass(Pass):
    """Strip fake quant-dequant chains at predictor load (the
    delete_quant_dequant_filter_op_pass.cc / delete_quant_dequant_op_pass
    family of framework/ir): a QAT model saved WITHOUT convert() carries
    the straight-through fake-quant program
        add(v, sub(mul(jit:clip(jit:round(mul(v, 1/s)), qmin, qmax), s), v))
    per quantized tensor; at inference the simulation noise serves nothing
    (the int8 payload + scales travel as metadata — qat._freeze), so every
    matched chain is replaced by its input value `v`."""

    name = "delete_quant_dequant"

    @staticmethod
    def _qdq_input(add_op):
        if add_op.name != "pd.add" or len(add_op.operands) != 2:
            return None
        v, s = add_op.operands
        sub = s.defining_op()
        if sub is None or sub.name != "pd.sub" or len(sub.operands) != 2:
            return None
        m, v2 = sub.operands
        if v2.id != v.id:
            return None
        mul = m.defining_op()
        if mul is None or mul.name != "pd.mul":
            return None
        clip = mul.operands[0].defining_op()
        if clip is None or clip.name != "pd.jit" or \
                clip.attrs().get("name") != "clip":
            return None
        rnd = clip.operands[0].defining_op()
        if rnd is None or rnd.name != "pd.jit" or \
                rnd.attrs().get("name") != "round":
            return None
        scale_mul = rnd.operands[0].defining_op()
        if scale_mul is None or scale_mul.name != "pd.mul":
            return None
        if scale_mul.operands[0].id != v.id:
            return None
        return v

    def run(self, program: Program) -> int:
        changed = 0
        for op in program.ops():
            v = self._qdq_input(op)
            if v is not None:
                n = op.result(0).replace_all_uses_with(v)
                erased = op.erase()
                if n or erased:
                    changed += 1
        if changed:
            program.dce()  # sweep the orphaned round/clip/scale chain
        return changed


_AFFINE_BYTES_LIMIT = 1 << 22  # don't materialize collapsed consts > 4 MiB


def _affine_step(name, operands, program):
    """If op is elementwise {add,sub,mul,div} with exactly one constant
    operand, return (data_value, m, b) describing y = m*x + b where x is
    the non-const operand; else None. `div` only folds a constant divisor;
    `sub` handles the constant on either side."""
    if name not in ("pd.add", "pd.sub", "pd.mul", "pd.div") or len(operands) != 2:
        return None
    a, b_ = operands
    ca, cb = _const_value(program, a), _const_value(program, b_)
    if (ca is None) == (cb is None):
        return None  # exactly one constant operand
    const = np.asarray(ca if ca is not None else cb)
    if not np.issubdtype(const.dtype, np.floating):
        return None
    data = b_ if ca is not None else a
    if name == "pd.add":
        return data, 1.0, const
    if name == "pd.mul":
        return data, const, 0.0
    if name == "pd.sub":
        if cb is not None:
            return data, 1.0, -const        # x - C
        return data, -1.0, const            # C - x
    if cb is not None:                       # x / C
        return data, 1.0 / const, 0.0
    return None                              # C / x is not affine


@register_pass
class AffineChainCollapsePass(Pass):
    """Collapse chains of elementwise ops with constant operands into one
    mul + one add (simplify_with_basic_ops / the arithmetic half of
    conv_bn_fuse_pass.cc): eval-mode BatchNorm traces to
    sub(mean)->mul(rsqrt)->mul(gamma)->add(beta) over the conv output; this
    rewrites the whole chain to y = M*x + B with M, B precomputed on host.

    Rewrite is by operand surgery on ops already in the chain (the IR has no
    op-reordering): one existing pd.mul becomes the M stage, the chain's
    last op becomes the B stage, everything between goes dead for DCE."""

    name = "affine_chain_collapse"

    def run(self, program: Program) -> int:
        changed = 0
        for last in program.ops():
            step = _affine_step(last.name, last.operands, program)
            if step is None:
                continue
            rtype = last.result(0).type
            # walk upward while ops stay affine, single-use, same-typed
            chain = [last]
            data, m, b = step
            while True:
                up = data.defining_op()
                if up is None or up.result(0).num_uses != 1:
                    break
                s = _affine_step(up.name, up.operands, program)
                if s is None or up.result(0).type != rtype:
                    break
                d2, m2, b2 = s
                # compose: y = m*(m2*x + b2) + b
                data, m, b = d2, np.asarray(m) * m2, np.asarray(m) * b2 + b
                chain.append(up)
            if len(chain) < 3:
                continue  # 1-2 ops are already minimal
            m, b = np.asarray(m), np.asarray(b)
            if m.nbytes > _AFFINE_BYTES_LIMIT or b.nbytes > _AFFINE_BYTES_LIMIT:
                continue
            mul_stage = next((op for op in chain if op.name == "pd.mul"), None)
            if mul_stage is None or last.name not in ("pd.add", "pd.sub"):
                continue  # need a mul to repurpose and an additive tail
            dtype = rtype.dtype if hasattr(rtype, "dtype") else m.dtype
            m_c = program.add_constant(m.astype(np.dtype(str(dtype)), copy=False),
                                       before=mul_stage)
            # B stage keeps `last`'s own opcode: add gets +B, sub gets -B
            b_v = b if last.name == "pd.add" else -b
            b_c = program.add_constant(b_v.astype(np.dtype(str(dtype)), copy=False),
                                       before=mul_stage)
            mul_stage.set_operand(0, data)
            mul_stage.set_operand(1, m_c.result(0))
            last.set_operand(0, mul_stage.result(0))
            last.set_operand(1, b_c.result(0))
            changed += 1
        if changed:
            program.dce()  # the bypassed chain interior is now dead
        return changed


@register_pass
class ConvBnFusePass(Pass):
    """Fold a per-output-channel constant scale into conv / matmul weights
    (conv_bn_fuse_pass.cc, conv_affine_channel_fuse_pass.cc, fc_fuse): after
    AffineChainCollapse the eval-BN residue is mul(conv(x, W), M) + add(B);
    when W is a baked constant (inference trace) the mul disappears into W,
    leaving conv + bias-add — the classic fused form."""

    name = "conv_bn_fuse"

    @staticmethod
    def _channel_vector(scale: np.ndarray, ch_dim: int, full_shape):
        """scale must be constant along every dim except `ch_dim` of the
        producing op's output; returns the length-C vector or None."""
        # right-align scale's shape against the output shape
        pad = len(full_shape) - len(scale.shape)
        if pad < 0:
            return None
        aligned = [1] * pad + list(scale.shape)
        for d, n in enumerate(aligned):
            if d != ch_dim and n != 1:
                return None
        if aligned[ch_dim] not in (1, full_shape[ch_dim]):
            return None
        if aligned[ch_dim] == 1:
            return np.asarray(
                np.broadcast_to(scale.reshape(-1)[:1], (full_shape[ch_dim],)))
        return np.asarray(np.broadcast_to(scale, aligned).reshape(-1))

    def run(self, program: Program) -> int:
        changed = 0
        for op in program.ops():
            if op.name != "pd.mul" or len(op.operands) != 2:
                continue
            prod_v, scale_v = op.operands
            scale = _const_value(program, scale_v)
            if scale is None:
                scale, prod_v = _const_value(program, prod_v), scale_v
            if scale is None:
                continue
            scale = np.asarray(scale)
            if not np.issubdtype(scale.dtype, np.floating):
                continue
            prod = prod_v.defining_op()
            if prod is None or prod.result(0).num_uses != 1 \
                    or prod.id not in program.op_bind:
                continue
            prim, params = program.op_bind[prod.id]
            out_shape = prod.result(0).type.shape
            if prod.name == "pd.conv_general_dilated":
                dn = params.get("dimension_numbers")
                if dn is None:
                    continue
                ch_dim, w_out_dim = dn.out_spec[1], dn.rhs_spec[0]
                w_idx = 1
            elif prod.name == "pd.dot_general":
                dn = params.get("dimension_numbers")
                if dn is None:
                    continue  # manually built op without dnums: skip, don't crash
                ((lc, rc), (lb, rb)) = dn
                if list(lb) or list(rb) or len(rc) != 1:
                    continue
                ch_dim = len(out_shape) - 1  # plain x @ W: out channel last
                w_rank = len(prod.operands[1].type.shape)
                if w_rank < 2:
                    continue  # matvec rhs has no free dim to scale
                # out dims are lhs-free then rhs-free IN ORDER, so the last
                # output dim maps to the LAST non-contracted rhs dim
                w_out_dim = max(d for d in range(w_rank) if d != rc[0])
                w_idx = 1
            else:
                continue
            W = _const_value(program, prod.operands[w_idx])
            if W is None:
                continue
            vec = self._channel_vector(scale, ch_dim, out_shape)
            if vec is None:
                continue
            W = np.asarray(W)
            bshape = [1] * W.ndim
            bshape[w_out_dim] = W.shape[w_out_dim]
            if W.shape[w_out_dim] != vec.shape[0]:
                continue
            newW = (W * vec.reshape(bshape)).astype(W.dtype, copy=False)
            prod.set_operand(w_idx,
                             program.add_constant(newW, before=prod).result(0))
            op.result(0).replace_all_uses_with(prod.result(0))
            op.erase()
            changed += 1
        return changed


def _skip_through(v, names=("pd.broadcast_in_dim", "pd.stop_gradient",
                            "pd.convert_element_type", "pd.reshape")):
    """Walk a value up through shape/metadata-only ops."""
    while True:
        op = v.defining_op()
        if op is None or op.name not in names:
            return v
        v = op.operands[0]


def _jit_name(program: Program, op) -> str:
    """The wrapped function's name for a pd.jit (pjit) op, '' otherwise."""
    if op is None or op.name != "pd.jit" or op.id not in program.op_bind:
        return ""
    _, params = program.op_bind[op.id]
    return str(params.get("name", ""))


def _eval_const_chain(program: Program, v, memo=None, limit=1 << 22):
    """Evaluate a value whose whole defining chain is constant (constants +
    side-effect-free ops), or None. The mask-recognition analog of
    ConstantFoldingPass — run once over a small subgraph at match time."""
    memo = {} if memo is None else memo
    if v.id in memo:
        return memo[v.id]
    cv = _const_value(program, v)
    if cv is not None:
        memo[v.id] = np.asarray(cv)
        return memo[v.id]
    op = v.defining_op()
    if op is None or op.has_side_effect:
        return None
    if sum(int(np.prod(r.type.shape or (1,))) for r in op.results) > limit:
        return None
    vals = []
    for o in op.operands:
        val = _eval_const_chain(program, o, memo, limit)
        if val is None:
            return None
        vals.append(val)
    try:
        if op.id in program.op_fns:
            out = program.op_fns[op.id](*vals)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
        elif op.id in program.op_bind:
            prim, params = program.op_bind[op.id]
            subfuns, bind_params = prim.get_bind_params(params)
            out = prim.bind(*subfuns, *vals, **bind_params)
            outs = list(out) if prim.multiple_results else [out]
        else:
            return None
    except Exception:
        return None
    for res, ov in zip(op.results, outs):
        memo[res.id] = np.asarray(ov)
    return memo.get(v.id)


# Per-intermediate element ceiling for mask-subgraph evaluation: 4096^2
# keeps a worst-case f32 intermediate at 64MB. Masks for longer sequences
# are simply not proven (the flash kernel still handles them via the
# explicit `causal` flag on the fused op); raise if a serving program
# genuinely traces a longer constant mask.
_MASK_EVAL_LIMIT = 4096 * 4096


def _is_causal_mask(program: Program, v, memo=None) -> bool:
    """True when `v` provably EVALUATES to the standard lower-triangular
    (diagonal-inclusive) boolean causal mask. Name-sniffing a tril jit is
    not enough — tril(k=-1) or tril of a non-ones matrix would fuse as
    standard causal and silently corrupt outputs — so the mask subgraph is
    evaluated and compared exactly. The static shape is screened BEFORE any
    evaluation (non-square or oversized masks never run the constant chain),
    and `memo` is shared across a pass run so a mask feeding every layer is
    evaluated once, not per attention site."""
    shp = tuple(getattr(v.type, "shape", None) or ())
    if len(shp) < 2 or shp[-1] != shp[-2] or any(d != 1 for d in shp[:-2]):
        return False
    if shp[-1] * shp[-1] > _MASK_EVAL_LIMIT:
        # the lost fusion must be visible (ADVICE r5): count + log the skip
        _metrics.counter("ir.causal_mask.skipped_oversized")
        _log.info(
            "causal-mask proof skipped: %dx%d mask exceeds _MASK_EVAL_LIMIT "
            "(%d elements); this attention site keeps the softmax+PV "
            "collapse instead of full flash fusion",
            shp[-1], shp[-1], _MASK_EVAL_LIMIT)
        return False
    m = _eval_const_chain(program, v, memo=memo, limit=_MASK_EVAL_LIMIT)
    if m is None or m.dtype != bool or m.ndim < 2:
        return False
    m2 = m.reshape(m.shape[-2], m.shape[-1])
    proven = bool(np.array_equal(m2, np.tril(np.ones_like(m2))))
    if proven:
        _metrics.counter("ir.causal_mask.proven")
    return proven


@register_pass
class MultiheadMatmulFusePass(Pass):
    """Fuse the decomposed attention subgraph into one op — the reference's
    multihead_matmul_fuse_pass.cc / fused softmax-mask kernel, TPU-native:
    the fused op re-binds to the Pallas flash-attention kernel on TPU (or
    the fused jnp SDPA elsewhere), so a traced-and-optimized serving program
    runs flash attention even though the trace recorded the decomposed form.

    Anchored on the probs@V dot_general; two tiers:
    * full fusion — softmax chain, a provably-causal (or absent) mask, the
      scaled Q@K^T dot all matched → pd.fused_multihead_attention(q, k, v).
    * softmax+PV collapse — unrecognized masking: the softmax chain and the
      PV matmul fuse into pd.fused_softmax_matmul(scores, v), leaving the
      mask arithmetic intact.
    """

    name = "multihead_matmul_fuse"

    @staticmethod
    def _reduce_axes(program: Program, op):
        if op is None or op.id not in program.op_bind:
            return None
        axes = program.op_bind[op.id][1].get("axes")
        return tuple(axes) if axes is not None else None

    def _match_softmax(self, program: Program, probs_v):
        """probs = div(exp(sub(s, rowmax)), bcast(reduce_sum(exp))) with the
        reductions over the KEY axis (3 of [b,h,q,k]) — a softmax over any
        other axis must not fuse as key-axis softmax. Returns the
        masked-scores value or None. The caller walks probs_v through
        converts first (bf16 traces cast f32 probs before the PV dot)."""
        div_op = probs_v.defining_op()
        if div_op is None or div_op.name != "pd.div":
            return None
        exp_v, denom_v = div_op.operands
        exp_op = exp_v.defining_op()
        if exp_op is None or exp_op.name != "pd.exp":
            return None
        den = _skip_through(denom_v).defining_op()
        if den is None or den.name != "pd.reduce_sum" \
                or den.operands[0].id != exp_v.id \
                or self._reduce_axes(program, den) != (3,):
            return None
        sub_op = exp_op.operands[0].defining_op()
        if sub_op is None or sub_op.name != "pd.sub":
            return None
        s_v, rowmax_v = sub_op.operands
        # the subtracted row-stat must reduce from the same scores over the
        # same key axis (walk through the max-clamp sdpa inserts for
        # fully-masked rows)
        rm = _skip_through(rowmax_v).defining_op()
        if rm is not None and rm.name == "pd.max":
            cands = [o for o in rm.operands
                     if _const_value(program, o) is None]
            rm = _skip_through(cands[0]).defining_op() if cands else None
        if rm is None or rm.name != "pd.reduce_max" \
                or rm.operands[0].id != s_v.id \
                or self._reduce_axes(program, rm) != (3,):
            return None
        return s_v

    def _match_qk(self, program: Program, s_v, memo=None):
        """s = [where-jit](mask, scores, fill) | scores;
        scores = dot(mul(q, c), k). Returns (q, k, scale, causal) or None."""
        causal = False
        sop = s_v.defining_op()
        if sop is not None and sop.name == "pd.jit" \
                and "where" in _jit_name(program, sop) \
                and len(sop.operands) == 3:
            mask_v, scores_v, fill_v = sop.operands
            fill = _const_value(program, fill_v)
            if fill is None or not np.all(np.asarray(fill) <= -1e20):
                return None
            if not _is_causal_mask(program, mask_v, memo=memo):
                return None  # additive/padding masks: tier-2 handles
            causal = True
            sop = scores_v.defining_op()
        if sop is None or sop.name != "pd.dot_general":
            return None
        # q/k must enter [b,s,h,d]: batch dims (0,2)=(b,h), contract d=3 on
        # BOTH sides (the einsum "bqhd,bkhd->bhqk" lowering) — anything else
        # would reorder the scores layout the softmax match assumed
        if sop.id not in program.op_bind:
            return None
        dn_s = program.op_bind[sop.id][1].get("dimension_numbers")
        if dn_s is None:
            return None
        (slc, src), (slb, srb) = dn_s
        if tuple(slb) != (0, 2) or tuple(srb) != (0, 2) \
                or tuple(slc) != (3,) or tuple(src) != (3,):
            return None
        qs_v, k_v = sop.operands
        scale = None
        qs_op = qs_v.defining_op()
        if qs_op is not None and qs_op.name == "pd.mul":
            for i in (1, 0):
                c = _const_value(program, qs_op.operands[i])
                if c is not None and np.asarray(c).size == 1:
                    scale = float(np.asarray(c).reshape(()))
                    qs_v = qs_op.operands[1 - i]
                    break
        if scale is None:
            scale = 1.0
        # q/k enter as [B, S, H, D] (the pre-einsum reshape outputs)
        if len(qs_v.type.shape) != 4 or len(k_v.type.shape) != 4:
            return None
        return qs_v, k_v, scale, causal

    @staticmethod
    def _pv_layout(program: Program, pv, probs_idx):
        """Validate the probs@V dot's dimension_numbers and derive the
        permutation from SDPA's natural [b, q, h, d] output to the dot's
        actual output layout. probs is [b, h, q, k] (guaranteed by the
        matched softmax/scores structure); V must be [b, s, h, d]. XLA's
        output dim order is batch dims then lhs-free then rhs-free — the
        orientation is NOT fixed (it emits [b,h,d,q] when V is the lhs), so
        it must be derived, not assumed. Returns the permutation or None."""
        if pv.id not in program.op_bind:
            return None
        _, params = program.op_bind[pv.id]
        dn = params.get("dimension_numbers")
        if dn is None:
            return None
        (lc, rc), (lb, rb) = dn
        if len(lc) != 1 or len(lb) != 2:
            return None
        # contraction/batch specs per operand role
        if probs_idx == 1:
            p_c, p_b, v_c, v_b = rc[0], tuple(rb), lc[0], tuple(lb)
        else:
            p_c, p_b, v_c, v_b = lc[0], tuple(lb), rc[0], tuple(rb)
        # probs [b,h,q,k]: batch (0,1) in order, contract k=3, free q=2
        if p_b != (0, 1) or p_c != 3:
            return None
        # v [b,s,h,d]: batch (0,2) pairing (b,h), contract s=1, free d=3
        if v_b != (0, 2) or v_c != 1:
            return None
        # output = batch(b,h) + lhs-free + rhs-free
        labels = ["b", "h"] + (["d", "q"] if probs_idx == 1 else ["q", "d"])
        sdpa_axis = {"b": 0, "q": 1, "h": 2, "d": 3}
        return tuple(sdpa_axis[l] for l in labels)

    def run(self, program: Program) -> int:
        changed = 0
        eval_memo: dict = {}  # mask-evaluation cache shared across matches
        for pv in program.ops():
            if pv.name != "pd.dot_general" or len(pv.operands) != 2:
                continue
            a, b = pv.operands
            if len(a.type.shape) != 4 or len(b.type.shape) != 4:
                continue
            # the probs operand is the one rooted in the softmax chain —
            # walked through converts (bf16 traces cast the f32 probs
            # before the PV dot; without this the pass is a silent no-op
            # for mixed-precision serving)
            s_v, probs_idx = None, None
            for idx, cand in ((1, b), (0, a)):
                s_v = self._match_softmax(
                    program,
                    _skip_through(cand, ("pd.convert_element_type",)))
                if s_v is not None:
                    probs_idx = idx
                    break
            if s_v is None:
                continue
            v_v = pv.operands[1 - probs_idx]
            perm = self._pv_layout(program, pv, probs_idx)
            if perm is None:
                continue
            # dtype name string: jnp.astype accepts it, incl. 'bfloat16'
            out_dtype = str(pv.result(0).type.dtype)
            qk = self._match_qk(program, s_v, memo=eval_memo)
            if qk is not None:
                q_v, k_v, scale, causal = qk

                def fused(q, k, v, _scale=scale, _causal=causal, _perm=perm,
                          _dt=out_dtype):
                    import jax.numpy as jnp

                    from ..nn.functional.attention import _sdpa_ref, _use_pallas

                    o = None
                    # flash kernel requires self-attention shapes (its
                    # blocks tile one shared seq length); below S=512 the
                    # decomposed XLA attention is at kernel parity and the
                    # pallas boundary only blocks fusion (measured r5)
                    if _use_pallas(q.dtype) and q.shape[1] == k.shape[1] \
                            and q.shape[1] >= 512:
                        from ..kernels.flash_attention import (
                            _pick_blocks, flash_attention_fwd)

                        if _pick_blocks(q.shape[1])[0] is not None:
                            o = flash_attention_fwd(q, k, v, causal=_causal,
                                                    scale=_scale)
                    if o is None:
                        o = _sdpa_ref(q, k, v, causal=_causal, scale=_scale)
                    return jnp.transpose(o, _perm).astype(_dt)

                op = program.create_op(
                    "pd.fused_multihead_attention", [q_v, k_v, v_v],
                    [pv.result(0).type],
                    attrs={"scale": scale, "causal": causal}, before=pv)
                program.op_fns[op.id] = fused
            else:
                def fused_sm(s, v, _perm=perm, _dt=out_dtype):
                    import jax
                    import jax.numpy as jnp

                    probs = jax.nn.softmax(s.astype(np.float32), axis=-1)
                    o = jnp.einsum("bhqk,bkhd->bqhd",
                                   probs.astype(v.dtype), v)
                    return jnp.transpose(o, _perm).astype(_dt)

                op = program.create_op(
                    "pd.fused_softmax_matmul", [s_v, v_v],
                    [pv.result(0).type], before=pv)
                program.op_fns[op.id] = fused_sm
            pv.result(0).replace_all_uses_with(op.result(0))
            pv.erase()
            changed += 1
        if changed:
            program.dce()  # the matched interior is now dead
        return changed


@register_pass
class GeluFusePass(Pass):
    """Collapse the traced 8-op tanh-approx GELU polynomial into one op
    (fc_elementwise_act / gelu fuse family of framework/ir): the pattern is
    mul(x, mul(0.5, add(1, tanh(mul(c, add(x, mul(0.044715, x^3))))))),
    byte-matched on the constants so lookalike arithmetic is left alone."""

    name = "gelu_fuse"

    @staticmethod
    def _const_scalar(program, v):
        c = _const_value(program, v)
        if c is None:
            return None
        c = np.asarray(c)
        return float(c.reshape(())) if c.size == 1 else None

    def _split_mul(self, program, op, want):
        """mul op with one const ≈ want: returns the non-const operand.
        Tolerance is loose (1%) because bf16 traces round the polynomial
        constants (0.044715 -> 0.044678); the surrounding structural match
        (x^3, tanh, the exact chain shape) carries the specificity."""
        if op is None or op.name != "pd.mul":
            return None
        for i in (0, 1):
            c = self._const_scalar(program, op.operands[i])
            if c is not None and abs(c - want) < 1e-2 * abs(want):
                return op.operands[1 - i]
        return None

    def run(self, program: Program) -> int:
        changed = 0
        for outer in program.ops():
            if outer.name != "pd.mul" or len(outer.operands) != 2:
                continue
            for xi in (0, 1):
                x_v, inner_v = outer.operands[xi], outer.operands[1 - xi]
                half_arg = self._split_mul(program, inner_v.defining_op(), 0.5)
                if half_arg is None:
                    continue
                add1 = half_arg.defining_op()
                if add1 is None or add1.name != "pd.add":
                    continue
                tanh_v = None
                for j in (0, 1):
                    c = self._const_scalar(program, add1.operands[j])
                    if c is not None and abs(c - 1.0) < 1e-6:
                        tanh_v = add1.operands[1 - j]
                if tanh_v is None:
                    continue
                tanh_op = tanh_v.defining_op()
                if tanh_op is None or tanh_op.name != "pd.tanh":
                    continue
                s_arg = self._split_mul(program, tanh_op.operands[0].defining_op(),
                                        float(np.sqrt(2.0 / np.pi)))
                if s_arg is None:
                    continue
                add2 = s_arg.defining_op()
                if add2 is None or add2.name != "pd.add":
                    continue
                cube_v = None
                for j in (0, 1):
                    if add2.operands[j].id == x_v.id:
                        cube_v = add2.operands[1 - j]
                if cube_v is None:
                    continue
                g_arg = self._split_mul(program, cube_v.defining_op(), 0.044715)
                if g_arg is None:
                    continue
                pow_op = g_arg.defining_op()
                if pow_op is None or pow_op.name != "pd.integer_pow" \
                        or pow_op.operands[0].id != x_v.id:
                    continue
                # the polynomial term must be exactly x^3 — an x^2/x^4
                # lookalike with the same chain shape is NOT gelu
                if pow_op.id not in program.op_bind \
                        or program.op_bind[pow_op.id][1].get("y") != 3:
                    continue

                def gelu(x):
                    from ..kernels.elementwise import tanh_gelu_raw

                    return tanh_gelu_raw(x)

                op = program.create_op("pd.gelu", [x_v],
                                       [outer.result(0).type],
                                       attrs={"approximate": True},
                                       before=outer)
                program.op_fns[op.id] = gelu
                outer.result(0).replace_all_uses_with(op.result(0))
                outer.erase()
                changed += 1
                break
        if changed:
            program.dce()
        return changed


def _bcast_of_1d(program: Program, v, size: int):
    """The affine-param idiom every normalization/bias site traces as:
    v = broadcast_in_dim(u) of a 1-D u of `size`, or (after constant
    folding collapses that broadcast) a CONSTANT shaped (1, ..., 1, size).
    Returns the parameter value or None. Consumers must reshape(-1) —
    the folded form keeps its leading 1s."""
    op = v.defining_op()
    if op is not None and op.name == "pd.broadcast_in_dim":
        u = op.operands[0]
        if tuple(u.type.shape) == (size,):
            return u
    shp = tuple(v.type.shape)
    if shp == (size,):
        return v
    if shp and shp[-1] == size and all(d == 1 for d in shp[:-1]) \
            and _const_value(program, v) is not None:
        return v
    return None


def _split_binary(program: Program, op, name, pred):
    """op must be `name`(a, b) with exactly one operand satisfying pred;
    returns (matched, other) or None. The either-operand-order helper all
    commutative patterns need."""
    if op is None or op.name != name or len(op.operands) != 2:
        return None
    for i in (0, 1):
        m = pred(op.operands[i])
        if m is not None:
            return m, op.operands[1 - i]
    return None


def _is_mean_of(program: Program, v, x_v, axis: int, n: int):
    """v == reduce_sum(x, axes=(axis,)) broadcast back keepdims then
    divided by n (or multiplied by 1/n). Returns True when v is the mean of
    x_v over `axis` — the exact chain nn.LayerNorm traces."""
    op = v.defining_op()
    if op is None:
        return False
    if op.name == "pd.div":
        c = _const_value(program, op.operands[1])
        if c is None or np.asarray(c).size != 1 \
                or abs(float(np.asarray(c).reshape(())) - n) > 1e-6 * n:
            return False
        v = op.operands[0]
    elif op.name == "pd.mul":
        got = _split_binary(
            program, op, "pd.mul",
            lambda o: o if (_const_value(program, o) is not None
                           and np.asarray(_const_value(program, o)).size == 1)
            else None)
        if got is None:
            return False
        cv, v = got
        if abs(float(np.asarray(_const_value(program, cv)).reshape(()))
               - 1.0 / n) > 1e-6 / n:
            return False
    else:
        return False
    op = v.defining_op()
    if op is not None and op.name == "pd.broadcast_in_dim":
        v = op.operands[0]
        op = v.defining_op()
    if op is None or op.name != "pd.reduce_sum" \
            or op.operands[0].id != x_v.id:
        return False
    axes = program.op_bind[op.id][1].get("axes") \
        if op.id in program.op_bind else None
    return axes is not None and tuple(axes) == (axis,)


@register_pass
class LayerNormFusePass(Pass):
    """Recompose the traced mean/var/rsqrt/affine chain into one
    pd.layer_norm op (the reference's layer_norm_fuse_pass.cc:1, which
    rebuilds LayerNorm from its decomposed form for the serving engines).
    TPU-native payoff: the single op re-binds to the Pallas fused_layer_norm
    kernel (kernels/norms.py) instead of the 15-op jnp chain, and it is the
    anchor EmbeddingEltwiseLayerNormFusePass matches on.

    Matched chain (exactly what nn.LayerNorm traces — see test):
        mu    = mean(x, -1, keepdims)            # sum/N or sum*(1/N)
        c     = sub(x, mu)                       # traced twice pre-CSE
        var   = mean(square(c), -1, keepdims)
        rstd  = rsqrt(add(var, eps))
        y     = add(mul(mul(c, rstd), bcast(gamma)), bcast(beta))
    Every reduction is verified to run over the LAST axis with N equal to
    its extent — a lookalike over another axis must not fuse."""

    name = "layer_norm_fuse"

    def run(self, program: Program) -> int:
        changed = 0
        for final in program.ops():
            if final.name != "pd.add" or len(final.operands) != 2:
                continue
            out_shape = tuple(final.result(0).type.shape)
            if not out_shape:
                continue
            H = out_shape[-1]
            axis = len(out_shape) - 1
            got = _split_binary(
                program, final, "pd.add",
                lambda v: _bcast_of_1d(program, v, H))
            if got is None:
                continue
            beta_v, scaled_v = got
            got = _split_binary(
                program, scaled_v.defining_op(), "pd.mul",
                lambda v: _bcast_of_1d(program, v, H))
            if got is None:
                continue
            gamma_v, normed_v = got

            def _rstd_like(v):
                op = v.defining_op()
                return v if (op is not None and op.name == "pd.rsqrt") \
                    else None

            got = _split_binary(program, normed_v.defining_op(), "pd.mul",
                                _rstd_like)
            if got is None:
                continue
            rstd_v, c2_v = got
            c2_op = c2_v.defining_op()
            if c2_op is None or c2_op.name != "pd.sub":
                continue
            x_v, mu_v = c2_op.operands
            if not _is_mean_of(program, mu_v, x_v, axis, H):
                continue
            # rstd = rsqrt(var + eps), var = mean(square(x - mu), -1)
            add_op = rstd_v.defining_op().operands[0].defining_op()
            if add_op is None or add_op.name != "pd.add":
                continue
            got = _split_binary(
                program, add_op, "pd.add",
                lambda v: v if (_const_value(program, v) is not None
                                and np.asarray(_const_value(program, v)).size
                                == 1) else None)
            if got is None:
                continue
            eps_v, var_v = got
            eps = float(np.asarray(_const_value(program, eps_v)).reshape(()))
            if not (0.0 < eps < 1e-2):
                continue
            var_op = var_v.defining_op()
            # unwrap the mean chain down to square(sub(x, mu)) and verify
            # the centered value matches the SAME x and mu
            vv = var_v
            # walk: mean(square(c1)) — reuse _is_mean_of on the square value
            sq_v = None
            op = vv.defining_op()
            if op is not None and op.name in ("pd.div", "pd.mul"):
                # locate the square feeding the reduction
                def find_sq(v, depth=0):
                    o = v.defining_op()
                    if o is None or depth > 4:
                        return None
                    if o.name == "pd.square":
                        return v
                    if o.name in ("pd.div", "pd.mul", "pd.broadcast_in_dim",
                                  "pd.reduce_sum"):
                        for operand in o.operands:
                            r = find_sq(operand, depth + 1)
                            if r is not None:
                                return r
                    return None
                sq_v = find_sq(vv)
            if sq_v is None or not _is_mean_of(program, var_v, sq_v, axis, H):
                continue
            c1_op = sq_v.defining_op().operands[0].defining_op()
            if c1_op is None or c1_op.name != "pd.sub" \
                    or c1_op.operands[0].id != x_v.id:
                continue
            mu1 = c1_op.operands[1]
            if mu1.id != mu_v.id \
                    and not _is_mean_of(program, mu1, x_v, axis, H):
                continue

            def ln(x, g, b, _eps=eps, _dt=str(final.result(0).type.dtype)):
                from ..kernels.elementwise import layer_norm_raw

                return layer_norm_raw(x, g, b, _eps).astype(_dt)

            op = program.create_op(
                "pd.layer_norm", [x_v, gamma_v, beta_v],
                [final.result(0).type], attrs={"epsilon": eps},
                before=final)
            program.op_fns[op.id] = ln
            final.result(0).replace_all_uses_with(op.result(0))
            final.erase()
            changed += 1
        if changed:
            program.dce()
        return changed


@register_pass
class FcFusePass(Pass):
    """matmul + bias-add (+ activation) -> pd.fused_fc (the reference's
    fc_fuse_pass.cc:1 + fc_elementwise_layernorm family). The activation is
    absorbed only when the bias-add's SOLE consumer is a recognized
    activation op — relu (the custom_jvp wrapper nn.functional.relu traces)
    or a pd.gelu produced by GeluFusePass (which therefore must run before
    this pass)."""

    name = "fc_fuse"

    @staticmethod
    def _act_of(program: Program, op):
        """Return 'relu'/'gelu' when op is a recognized activation."""
        if op is None:
            return None
        if op.name == "pd.gelu":
            return "gelu"
        if op.name == "pd.custom_jvp_call" and op.id in program.op_bind:
            cj = program.op_bind[op.id][1].get("call_jaxpr")
            try:
                eqns = cj.jaxpr.eqns
            except AttributeError:
                eqns = getattr(cj, "eqns", [])
            for e in eqns:
                if str(e.params.get("name", "")) == "relu":
                    return "relu"
        return None

    def run(self, program: Program) -> int:
        changed = 0
        for add in program.ops():
            if add.name != "pd.add" or len(add.operands) != 2:
                continue
            out_shape = tuple(add.result(0).type.shape)
            if not out_shape:
                continue
            H = out_shape[-1]
            got = _split_binary(program, add, "pd.add",
                                lambda v: _bcast_of_1d(program, v, H))
            if got is None:
                continue
            bias_v, dot_v = got
            # bf16 Linears trace dot(preferred f32) -> convert -> add: walk
            # through the convert and reproduce it in the fused op
            mid_v = _skip_through(dot_v, ("pd.convert_element_type",))
            dot = mid_v.defining_op()
            if dot is None or dot.name != "pd.dot_general" \
                    or dot.id not in program.op_bind:
                continue
            acc_dtype = str(mid_v.type.dtype)  # the dot's own result dtype
            mid_dtype = str(dot_v.type.dtype)  # post-convert (= add input)
            dn = program.op_bind[dot.id][1].get("dimension_numbers")
            if dn is None:
                continue
            (lc, rc), (lb, rb) = dn
            x_v, w_v = dot.operands
            # the Linear lowering: contract x's last dim against W dim 0,
            # no batch dims, W rank-2 — anything else is not an FC
            if lb or rb or len(w_v.type.shape) != 2 \
                    or tuple(lc) != (len(x_v.type.shape) - 1,) \
                    or tuple(rc) != (0,):
                continue
            # absorb a sole-consumer activation (users scanned at match
            # time — a cached map would go stale across fusions)
            target = add
            act = "none"
            if add.result(0).num_uses == 1:
                rid = add.result(0).id
                user = next((o for o in program.ops()
                             if any(v.id == rid for v in o.operands)), None)
                a = self._act_of(program, user)
                if a is not None:
                    target, act = user, a

            def fc(x, w, b, _act=act, _acc=acc_dtype, _mid=mid_dtype,
                   _dt=str(target.result(0).type.dtype)):
                import jax.numpy as jnp

                from ..kernels.elementwise import tanh_gelu_raw

                y = jnp.matmul(x, w, preferred_element_type=_acc)
                y = y.astype(_mid) + b
                if _act == "relu":
                    y = jnp.maximum(y, 0)
                elif _act == "gelu":
                    y = tanh_gelu_raw(y)
                return y.astype(_dt)

            op = program.create_op(
                "pd.fused_fc", [x_v, w_v, bias_v],
                [target.result(0).type], attrs={"activation": act},
                before=target)
            program.op_fns[op.id] = fc
            target.result(0).replace_all_uses_with(op.result(0))
            target.erase()
            changed += 1
        if changed:
            program.dce()
        return changed


@register_pass
class EmbeddingEltwiseLayerNormFusePass(Pass):
    """N embedding lookups summed then layer-normalized -> one op (the
    reference's trt_embedding_eltwise_layernorm_fuse_pass — the BERT serving
    input block: word + position [+ type] embeddings). Anchors on the
    pd.layer_norm op LayerNormFusePass produced (so it must run after it)
    whose input is an add-tree of pd.jit[_take] gathers."""

    name = "embedding_eltwise_layernorm_fuse"

    @staticmethod
    def _take_operands(program: Program, v):
        """v = jnp.take(table, ids) trace: pd.jit named _take over
        (table 2-D, ids int). Returns (table_v, ids_v) or None."""
        op = v.defining_op()
        if op is None or op.name != "pd.jit" \
                or _jit_name(program, op) != "_take" \
                or len(op.operands) != 2:
            return None
        table_v, ids_v = op.operands
        if len(table_v.type.shape) != 2 \
                or not str(ids_v.type.dtype).startswith(("int", "uint")):
            return None
        return table_v, ids_v

    def _collect_lookups(self, program: Program, v, out, depth=0):
        """Flatten an add-tree whose every leaf is a _take gather."""
        tk = self._take_operands(program, v)
        if tk is not None:
            out.append(tk)
            return True
        op = v.defining_op()
        if op is None or op.name != "pd.add" or depth > 4:
            return False
        return all(self._collect_lookups(program, o, out, depth + 1)
                   for o in op.operands)

    def run(self, program: Program) -> int:
        changed = 0
        for ln in program.ops():
            if ln.name != "pd.layer_norm":
                continue
            x_v, gamma_v, beta_v = ln.operands
            lookups: list = []
            if not self._collect_lookups(program, x_v, lookups) \
                    or len(lookups) < 2:
                continue
            eps = float(ln.attrs().get("epsilon", 1e-5))
            n_emb = len(lookups)

            def fused(*args, _n=n_emb, _eps=eps,
                      _dt=str(ln.result(0).type.dtype)):
                import jax.numpy as jnp

                from ..kernels.elementwise import layer_norm_raw

                tables, ids = args[:_n], args[_n:2 * _n]
                g, b = args[2 * _n], args[2 * _n + 1]
                x = sum(jnp.take(t, i, axis=0)
                        for t, i in zip(tables, ids))
                return layer_norm_raw(x, g, b, _eps).astype(_dt)

            operands = ([t for t, _ in lookups] + [i for _, i in lookups]
                        + [gamma_v, beta_v])
            op = program.create_op(
                "pd.fused_embedding_eltwise_layernorm", operands,
                [ln.result(0).type],
                attrs={"epsilon": eps, "num_embeddings": n_emb}, before=ln)
            program.op_fns[op.id] = fused
            ln.result(0).replace_all_uses_with(op.result(0))
            ln.erase()
            changed += 1
        if changed:
            program.dce()
        return changed


@register_pass
class SkipLayerNormFusePass(Pass):
    """residual add + layer norm -> one pd.fused_skip_layernorm op (the
    reference's skip_layernorm_fuse_pass, the transformer residual seam
    BERT/ERNIE serving hits twice per block). Anchors on the pd.layer_norm
    ops LayerNormFusePass produced, so it runs after it — and after
    EmbeddingEltwiseLayerNormFusePass, which claims the input-block
    add-trees first. Constants are excluded (an add with a constant is a
    bias, not a residual seam — AffineChainCollapse territory)."""

    name = "skip_layernorm_fuse"

    def run(self, program: Program) -> int:
        changed = 0
        for ln in program.ops():
            if ln.name != "pd.layer_norm":
                continue
            x_v, gamma_v, beta_v = ln.operands
            add = x_v.defining_op()
            if add is None or add.name != "pd.add" or len(add.operands) != 2:
                continue
            u_v, w_v = add.operands
            if _const_value(program, u_v) is not None \
                    or _const_value(program, w_v) is not None:
                continue
            if tuple(u_v.type.shape) != tuple(w_v.type.shape):
                continue  # broadcasted add: not the residual seam
            eps = float(ln.attrs().get("epsilon", 1e-5))

            def fused(u, w, g, b, _eps=eps,
                      _dt=str(ln.result(0).type.dtype)):
                from ..kernels.elementwise import layer_norm_raw

                return layer_norm_raw(u + w, g, b, _eps).astype(_dt)

            op = program.create_op(
                "pd.fused_skip_layernorm", [u_v, w_v, gamma_v, beta_v],
                [ln.result(0).type], attrs={"epsilon": eps}, before=ln)
            program.op_fns[op.id] = fused
            ln.result(0).replace_all_uses_with(op.result(0))
            ln.erase()
            changed += 1
        if changed:
            program.dce()
        return changed


@register_pass
class DropoutEliminatePass(Pass):
    """Inference-only: pd.dropout → identity (delete_dropout_op_pass analog).

    Programs traced from layers in eval() mode never contain dropout (the
    Python layer gates it), so this matters only for IR built directly or
    traced in train mode for deployment."""

    name = "dropout_eliminate"

    def run(self, program: Program) -> int:
        changed = 0
        for op in program.ops():
            if op.name in ("pd.dropout", "dropout"):
                n = op.result(0).replace_all_uses_with(op.operands[0])
                erased = op.erase()
                if n or erased:
                    changed += 1
        return changed
