"""Builtin passes — the TPU-relevant core of the reference's 268-file
fluid/framework/ir pass library.

Kept deliberately small: on TPU, XLA owns fusion/layout/scheduling, so the
passes that still pay are the PROGRAM-level ones XLA can't see across the
trace boundary — constant folding (pre-computing frozen subgraphs, which
subsumes most of conv_bn_fuse's arithmetic once BN runs in eval mode),
algebraic identity cleanup, CSE and DCE (native, ir_core.cc), and
inference-only rewrites (dropout elimination). Pattern passes use simple
def-use matching — the GraphPatternDetector analog over Value.defining_op().
"""

from __future__ import annotations

import numpy as np

from .core import CONSTANT_OP, Program
from .pass_manager import Pass, register_pass

_FOLD_ELEMENT_LIMIT = 1 << 22  # don't materialize folded constants > 4M elems


@register_pass
class DeadCodeEliminationPass(Pass):
    """Native reverse-sweep DCE (framework/ir delete_op_device_pass family)."""

    name = "dce"

    def run(self, program: Program) -> int:
        return program.dce()


@register_pass
class CommonSubexpressionEliminationPass(Pass):
    """Native structural CSE over (name, operands, attrs, result types)."""

    name = "cse"

    def run(self, program: Program) -> int:
        return program.cse()


def _const_value(program: Program, v):
    op = v.defining_op()
    if op is None or op.name != CONSTANT_OP:
        return None
    return program.const_vals.get(op.id)


@register_pass
class ConstantFoldingPass(Pass):
    """Evaluate side-effect-free ops whose operands are all constants
    (constant_folding_pass.cc analog). Evaluation re-binds the primitive on
    the concrete values — i.e. runs it eagerly through XLA once, at
    optimization time instead of every execution."""

    name = "constant_folding"

    def run(self, program: Program) -> int:
        changed = 0
        for op in program.ops():
            if op.name == CONSTANT_OP or op.has_side_effect:
                continue
            if op.id not in program.op_bind:
                continue
            vals = []
            all_const = True
            for operand in op.operands:
                cv = _const_value(program, operand)
                if cv is None:
                    all_const = False
                    break
                vals.append(cv)
            if not all_const:
                continue
            out_elems = sum(int(np.prod(r.type.shape or (1,))) for r in op.results)
            if out_elems > _FOLD_ELEMENT_LIMIT:
                continue
            prim, params = program.op_bind[op.id]
            try:
                subfuns, bind_params = prim.get_bind_params(params)
                folded = prim.bind(*subfuns, *vals, **bind_params)
            except Exception:
                continue  # unfoldable (needs trace context) — leave as-is
            if not prim.multiple_results:
                folded = [folded]
            for res, fv in zip(op.results, folded):
                res.replace_all_uses_with(program.add_constant(np.asarray(fv)).result(0))
            op.erase()  # now dead; erasing here keeps re-runs convergent
            changed += 1
        return changed


def _is_const_filled(program: Program, v, scalar) -> bool:
    cv = _const_value(program, v)
    if cv is None:
        return False
    try:
        return bool(np.all(np.asarray(cv) == scalar))
    except Exception:
        return False


@register_pass
class AlgebraicSimplifyPass(Pass):
    """Identity cleanup: x+0, x-0, x*1, x/1, double-transpose, no-op convert
    (the simplify_* / identity_op_clean passes of framework/ir)."""

    name = "algebraic_simplify"

    def run(self, program: Program) -> int:
        changed = 0
        for op in program.ops():
            name = op.name
            repl = None
            if name in ("pd.add", "pd.sub") and len(op.operands) == 2:
                a, b = op.operands
                if _is_const_filled(program, b, 0) and b.type == a.type == op.result(0).type:
                    repl = a
                elif name == "pd.add" and _is_const_filled(program, a, 0) \
                        and a.type == b.type == op.result(0).type:
                    repl = b
            elif name in ("pd.mul", "pd.div") and len(op.operands) == 2:
                a, b = op.operands
                if _is_const_filled(program, b, 1) and b.type == a.type == op.result(0).type:
                    repl = a
                elif name == "pd.mul" and _is_const_filled(program, a, 1) \
                        and a.type == b.type == op.result(0).type:
                    repl = b
            elif name == "pd.transpose":
                inner = op.operands[0].defining_op()
                if inner is not None and inner.name == "pd.transpose":
                    outer_p = op.attrs().get("permutation")
                    inner_p = inner.attrs().get("permutation")
                    if outer_p and inner_p and \
                            [inner_p[p] for p in outer_p] == list(range(len(outer_p))):
                        repl = inner.operands[0]
            elif name == "pd.convert_element_type":
                if op.result(0).type == op.operands[0].type:
                    repl = op.operands[0]
            if repl is not None:
                n = op.result(0).replace_all_uses_with(repl)
                erased = op.erase()
                if n or erased:  # count real rewrites only, or convergence
                    changed += 1  # detection never settles
        return changed


@register_pass
class DeleteQuantDequantPass(Pass):
    """Strip fake quant-dequant chains at predictor load (the
    delete_quant_dequant_filter_op_pass.cc / delete_quant_dequant_op_pass
    family of framework/ir): a QAT model saved WITHOUT convert() carries
    the straight-through fake-quant program
        add(v, sub(mul(jit:clip(jit:round(mul(v, 1/s)), qmin, qmax), s), v))
    per quantized tensor; at inference the simulation noise serves nothing
    (the int8 payload + scales travel as metadata — qat._freeze), so every
    matched chain is replaced by its input value `v`."""

    name = "delete_quant_dequant"

    @staticmethod
    def _qdq_input(add_op):
        if add_op.name != "pd.add" or len(add_op.operands) != 2:
            return None
        v, s = add_op.operands
        sub = s.defining_op()
        if sub is None or sub.name != "pd.sub" or len(sub.operands) != 2:
            return None
        m, v2 = sub.operands
        if v2.id != v.id:
            return None
        mul = m.defining_op()
        if mul is None or mul.name != "pd.mul":
            return None
        clip = mul.operands[0].defining_op()
        if clip is None or clip.name != "pd.jit" or \
                clip.attrs().get("name") != "clip":
            return None
        rnd = clip.operands[0].defining_op()
        if rnd is None or rnd.name != "pd.jit" or \
                rnd.attrs().get("name") != "round":
            return None
        scale_mul = rnd.operands[0].defining_op()
        if scale_mul is None or scale_mul.name != "pd.mul":
            return None
        if scale_mul.operands[0].id != v.id:
            return None
        return v

    def run(self, program: Program) -> int:
        changed = 0
        for op in program.ops():
            v = self._qdq_input(op)
            if v is not None:
                n = op.result(0).replace_all_uses_with(v)
                erased = op.erase()
                if n or erased:
                    changed += 1
        if changed:
            program.dce()  # sweep the orphaned round/clip/scale chain
        return changed


_AFFINE_BYTES_LIMIT = 1 << 22  # don't materialize collapsed consts > 4 MiB


def _affine_step(name, operands, program):
    """If op is elementwise {add,sub,mul,div} with exactly one constant
    operand, return (data_value, m, b) describing y = m*x + b where x is
    the non-const operand; else None. `div` only folds a constant divisor;
    `sub` handles the constant on either side."""
    if name not in ("pd.add", "pd.sub", "pd.mul", "pd.div") or len(operands) != 2:
        return None
    a, b_ = operands
    ca, cb = _const_value(program, a), _const_value(program, b_)
    if (ca is None) == (cb is None):
        return None  # exactly one constant operand
    const = np.asarray(ca if ca is not None else cb)
    if not np.issubdtype(const.dtype, np.floating):
        return None
    data = b_ if ca is not None else a
    if name == "pd.add":
        return data, 1.0, const
    if name == "pd.mul":
        return data, const, 0.0
    if name == "pd.sub":
        if cb is not None:
            return data, 1.0, -const        # x - C
        return data, -1.0, const            # C - x
    if cb is not None:                       # x / C
        return data, 1.0 / const, 0.0
    return None                              # C / x is not affine


@register_pass
class AffineChainCollapsePass(Pass):
    """Collapse chains of elementwise ops with constant operands into one
    mul + one add (simplify_with_basic_ops / the arithmetic half of
    conv_bn_fuse_pass.cc): eval-mode BatchNorm traces to
    sub(mean)->mul(rsqrt)->mul(gamma)->add(beta) over the conv output; this
    rewrites the whole chain to y = M*x + B with M, B precomputed on host.

    Rewrite is by operand surgery on ops already in the chain (the IR has no
    op-reordering): one existing pd.mul becomes the M stage, the chain's
    last op becomes the B stage, everything between goes dead for DCE."""

    name = "affine_chain_collapse"

    def run(self, program: Program) -> int:
        changed = 0
        for last in program.ops():
            step = _affine_step(last.name, last.operands, program)
            if step is None:
                continue
            rtype = last.result(0).type
            # walk upward while ops stay affine, single-use, same-typed
            chain = [last]
            data, m, b = step
            while True:
                up = data.defining_op()
                if up is None or up.result(0).num_uses != 1:
                    break
                s = _affine_step(up.name, up.operands, program)
                if s is None or up.result(0).type != rtype:
                    break
                d2, m2, b2 = s
                # compose: y = m*(m2*x + b2) + b
                data, m, b = d2, np.asarray(m) * m2, np.asarray(m) * b2 + b
                chain.append(up)
            if len(chain) < 3:
                continue  # 1-2 ops are already minimal
            m, b = np.asarray(m), np.asarray(b)
            if m.nbytes > _AFFINE_BYTES_LIMIT or b.nbytes > _AFFINE_BYTES_LIMIT:
                continue
            mul_stage = next((op for op in chain if op.name == "pd.mul"), None)
            if mul_stage is None or last.name not in ("pd.add", "pd.sub"):
                continue  # need a mul to repurpose and an additive tail
            dtype = rtype.dtype if hasattr(rtype, "dtype") else m.dtype
            m_c = program.add_constant(m.astype(np.dtype(str(dtype)), copy=False))
            # B stage keeps `last`'s own opcode: add gets +B, sub gets -B
            b_v = b if last.name == "pd.add" else -b
            b_c = program.add_constant(b_v.astype(np.dtype(str(dtype)), copy=False))
            mul_stage.set_operand(0, data)
            mul_stage.set_operand(1, m_c.result(0))
            last.set_operand(0, mul_stage.result(0))
            last.set_operand(1, b_c.result(0))
            changed += 1
        if changed:
            program.dce()  # the bypassed chain interior is now dead
        return changed


@register_pass
class ConvBnFusePass(Pass):
    """Fold a per-output-channel constant scale into conv / matmul weights
    (conv_bn_fuse_pass.cc, conv_affine_channel_fuse_pass.cc, fc_fuse): after
    AffineChainCollapse the eval-BN residue is mul(conv(x, W), M) + add(B);
    when W is a baked constant (inference trace) the mul disappears into W,
    leaving conv + bias-add — the classic fused form."""

    name = "conv_bn_fuse"

    @staticmethod
    def _channel_vector(scale: np.ndarray, ch_dim: int, full_shape):
        """scale must be constant along every dim except `ch_dim` of the
        producing op's output; returns the length-C vector or None."""
        # right-align scale's shape against the output shape
        pad = len(full_shape) - len(scale.shape)
        if pad < 0:
            return None
        aligned = [1] * pad + list(scale.shape)
        for d, n in enumerate(aligned):
            if d != ch_dim and n != 1:
                return None
        if aligned[ch_dim] not in (1, full_shape[ch_dim]):
            return None
        if aligned[ch_dim] == 1:
            return np.asarray(
                np.broadcast_to(scale.reshape(-1)[:1], (full_shape[ch_dim],)))
        return np.asarray(np.broadcast_to(scale, aligned).reshape(-1))

    def run(self, program: Program) -> int:
        changed = 0
        for op in program.ops():
            if op.name != "pd.mul" or len(op.operands) != 2:
                continue
            prod_v, scale_v = op.operands
            scale = _const_value(program, scale_v)
            if scale is None:
                scale, prod_v = _const_value(program, prod_v), scale_v
            if scale is None:
                continue
            scale = np.asarray(scale)
            if not np.issubdtype(scale.dtype, np.floating):
                continue
            prod = prod_v.defining_op()
            if prod is None or prod.result(0).num_uses != 1 \
                    or prod.id not in program.op_bind:
                continue
            prim, params = program.op_bind[prod.id]
            out_shape = prod.result(0).type.shape
            if prod.name == "pd.conv_general_dilated":
                dn = params.get("dimension_numbers")
                if dn is None:
                    continue
                ch_dim, w_out_dim = dn.out_spec[1], dn.rhs_spec[0]
                w_idx = 1
            elif prod.name == "pd.dot_general":
                dn = params.get("dimension_numbers")
                if dn is None:
                    continue  # manually built op without dnums: skip, don't crash
                ((lc, rc), (lb, rb)) = dn
                if list(lb) or list(rb) or len(rc) != 1:
                    continue
                ch_dim = len(out_shape) - 1  # plain x @ W: out channel last
                w_rank = len(prod.operands[1].type.shape)
                if w_rank < 2:
                    continue  # matvec rhs has no free dim to scale
                # out dims are lhs-free then rhs-free IN ORDER, so the last
                # output dim maps to the LAST non-contracted rhs dim
                w_out_dim = max(d for d in range(w_rank) if d != rc[0])
                w_idx = 1
            else:
                continue
            W = _const_value(program, prod.operands[w_idx])
            if W is None:
                continue
            vec = self._channel_vector(scale, ch_dim, out_shape)
            if vec is None:
                continue
            W = np.asarray(W)
            bshape = [1] * W.ndim
            bshape[w_out_dim] = W.shape[w_out_dim]
            if W.shape[w_out_dim] != vec.shape[0]:
                continue
            newW = (W * vec.reshape(bshape)).astype(W.dtype, copy=False)
            prod.set_operand(w_idx, program.add_constant(newW).result(0))
            op.result(0).replace_all_uses_with(prod.result(0))
            op.erase()
            changed += 1
        return changed


@register_pass
class DropoutEliminatePass(Pass):
    """Inference-only: pd.dropout → identity (delete_dropout_op_pass analog).

    Programs traced from layers in eval() mode never contain dropout (the
    Python layer gates it), so this matters only for IR built directly or
    traced in train mode for deployment."""

    name = "dropout_eliminate"

    def run(self, program: Program) -> int:
        changed = 0
        for op in program.ops():
            if op.name in ("pd.dropout", "dropout"):
                n = op.result(0).replace_all_uses_with(op.operands[0])
                erased = op.erase()
                if n or erased:
                    changed += 1
        return changed
