"""Differential fuzz harness for the IR pass pipeline.

Generates small random tensor programs (seeded — reproducible by seed),
traces each into a Program, runs a pass pipeline with the structural
verifier forced ON, and checks the optimized callable's numerics against
the untraced original on the same inputs. A pass that miscompiles (wrong
fold, bad rewire, dropped op) shows up either as a verifier violation or
as a numeric mismatch; both are reported per seed.

Used by tests/test_analysis.py (a handful of seeds per run) and available
standalone::

    python -m paddle_tpu.ir.fuzz --num 50 --seed 0
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["FuzzFailure", "random_program", "check_seed", "run_fuzz"]

_SHAPE = (4, 4)  # uniform shape: every binary op / matmul composes


@dataclasses.dataclass
class FuzzFailure:
    seed: int
    stage: str      # "trace" | "passes" | "verify" | "emit" | "numerics"
    detail: str

    def __str__(self):
        return f"[seed {self.seed}] {self.stage}: {self.detail}"


def random_program(rng: np.random.Generator,
                   n_inputs: int = 2,
                   n_ops: int = 12) -> Tuple[Callable, Tuple[np.ndarray, ...]]:
    """Build a random closed-over op recipe and example args.

    The recipe is a static list (op name, operand indices, optional
    constant), so calling the returned fn twice — once raw, once traced —
    executes the identical computation.
    """
    import jax.numpy as jnp

    n_vals = n_inputs
    recipe = []
    for _ in range(n_ops):
        kind = rng.choice(["add", "sub", "mul", "maximum", "tanh", "neg",
                           "matmul", "const_mul", "const_add"])
        a = int(rng.integers(n_vals))
        b = int(rng.integers(n_vals))
        const = None
        if kind in ("const_mul", "const_add"):
            # scalars sometimes, tensors sometimes — both feed the
            # constant-folding / affine-collapse paths
            if rng.random() < 0.5:
                const = np.float32(rng.normal())
            else:
                const = rng.normal(size=_SHAPE).astype(np.float32)
        recipe.append((str(kind), a, b, const))
        n_vals += 1
    out_idx = [int(rng.integers(n_vals)) for _ in range(2)]

    def fn(*xs):
        vals = list(xs)
        for kind, a, b, const in recipe:
            va, vb = vals[a], vals[b]
            if kind == "add":
                v = va + vb
            elif kind == "sub":
                v = va - vb
            elif kind == "mul":
                v = va * vb
            elif kind == "maximum":
                v = jnp.maximum(va, vb)
            elif kind == "tanh":
                v = jnp.tanh(va)
            elif kind == "neg":
                v = -va
            elif kind == "matmul":
                v = va @ vb
            elif kind == "const_mul":
                v = va * const
            else:  # const_add
                v = va + const
            vals.append(v)
        return tuple(vals[i] for i in out_idx)

    args = tuple(rng.normal(size=_SHAPE).astype(np.float32)
                 for _ in range(n_inputs))
    return fn, args


def check_seed(seed: int, passes: Optional[Sequence[str]] = None,
               n_ops: int = 12, rtol: float = 1e-4,
               atol: float = 1e-5) -> Optional[FuzzFailure]:
    """Trace/optimize/re-emit one random program; None means it passed."""
    from ..core import flags as _flags
    from . import trace
    from .pass_manager import PassManager, PassVerificationError
    from .verifier import verify_structure

    rng = np.random.default_rng(seed)
    fn, args = random_program(rng, n_ops=n_ops)
    expected = fn(*args)

    try:
        prog = trace(fn, *args)
    except Exception as e:  # generator bug, not a pass bug — still surface
        return FuzzFailure(seed, "trace", repr(e))

    prev = _flags.flag_value("ir_verify")
    _flags.set_flags({"ir_verify": True})  # force verifier even outside pytest
    try:
        pm = PassManager(passes)
        pm.run(prog)
    except PassVerificationError as e:
        return FuzzFailure(seed, "verify", str(e))
    except Exception as e:
        return FuzzFailure(seed, "passes", repr(e))
    finally:
        _flags.set_flags({"ir_verify": prev})

    errs = verify_structure(prog)
    if errs:
        return FuzzFailure(seed, "verify", "; ".join(errs[:4]))

    try:
        got = prog.to_callable()(*args)
    except Exception as e:
        return FuzzFailure(seed, "emit", repr(e))

    for i, (e, g) in enumerate(zip(expected, got)):
        if not np.allclose(np.asarray(e), np.asarray(g), rtol=rtol, atol=atol):
            delta = float(np.max(np.abs(np.asarray(e) - np.asarray(g))))
            return FuzzFailure(seed, "numerics",
                               f"output {i} max|delta|={delta:.3e}")
    return None


def run_fuzz(num: int = 20, seed0: int = 0,
             passes: Optional[Sequence[str]] = None) -> List[FuzzFailure]:
    """Check ``num`` consecutive seeds; returns the failures (empty = clean)."""
    failures = []
    for s in range(seed0, seed0 + num):
        f = check_seed(s, passes=passes)
        if f is not None:
            failures.append(f)
    return failures


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--num", type=int, default=20)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--pipeline", default=None,
                   help="comma-separated pass names (default pipeline if unset)")
    ns = p.parse_args(argv)
    passes = ns.pipeline.split(",") if ns.pipeline else None
    failures = run_fuzz(ns.num, ns.seed, passes)
    for f in failures:
        print(f)
    print(f"{ns.num} seed(s), {len(failures)} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
