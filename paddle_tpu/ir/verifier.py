"""Structural verifier for ir.Program — runs after every pass.

The native ``ir_verify`` covers the storage-level invariants (alive-id
consistency); this module adds the PROGRAM-level invariants a rewriting
pass can silently break while the native check still passes:

- **def-before-use**: every operand's defining op appears earlier in
  program order (``Program.ops()``); block arguments are position-free.
  ``to_callable`` hoists constants before re-emission, which would MASK a
  pass that appends a constant after its users — so the verifier enforces
  strict program order for constants too (see the ``before=`` argument of
  ``Program.add_constant``, added for exactly this).
- **no dangling Values**: no operand or program output refers to an erased
  op's result.
- **operand/result type agreement**: for primitive-bound ops the declared
  result types must match what the primitive abstract-evals to on the
  operand types (``jax.eval_shape``); a pass that rewires operands without
  recomputing result types is caught here, not at re-emission time.

Gated by the ``ir_verify`` flag (``paddle_tpu.core.flags``): default is
auto — ON under pytest (``PYTEST_CURRENT_TEST`` set), off otherwise so
production pipelines don't pay the abstract-eval cost per pass. Set the
flag to True/False to force either way.
"""

from __future__ import annotations

import os
from typing import List

from ..core import flags as _flags
from ..observability import metrics as _metrics
from .core import CONSTANT_OP, Program

__all__ = ["PassVerificationError", "verification_enabled", "verify_structure"]

_flags.register_flag(
    "ir_verify", None,
    "Run the structural IR verifier after every pass "
    "(None = auto: on under pytest)")


class PassVerificationError(RuntimeError):
    """A pass left the program structurally invalid."""


def verification_enabled() -> bool:
    val = _flags.flag_value("ir_verify")
    if val is None:
        return "PYTEST_CURRENT_TEST" in os.environ
    return bool(val)


def _type_str(t) -> str:
    try:
        return f"{t.dtype}{list(t.shape)}"
    except Exception:
        return "<?>"


def verify_structure(program: Program) -> List[str]:
    """Check program-order/def-use/type invariants; returns human-readable
    violation strings (empty list = clean). Never raises on malformed
    programs — callers decide whether findings are fatal."""
    errors: List[str] = []
    ops = program.ops()
    pos = {op.id: i for i, op in enumerate(ops)}
    block_args = {v.id for v in program.inputs}

    for i, op in enumerate(ops):
        for j, operand in enumerate(op.operands):
            d = operand.defining_op()
            if d is None:
                if operand.id not in block_args:
                    errors.append(
                        f"op {op.id} '{op.name}' operand {j}: value "
                        f"%{operand.id} has no defining op and is not a "
                        "block argument (dangling)")
                continue
            if d.id not in pos:
                errors.append(
                    f"op {op.id} '{op.name}' operand {j}: defined by "
                    f"erased op {d.id} (dangling)")
            elif pos[d.id] >= i:
                errors.append(
                    f"op {op.id} '{op.name}' operand {j}: defined by op "
                    f"{d.id} '{d.name}' at position {pos[d.id]} >= {i} "
                    "(def-before-use violated)")

    for k, out in enumerate(program.outputs):
        d = out.defining_op()
        if d is None:
            if out.id not in block_args:
                errors.append(f"program output {k}: value %{out.id} is "
                              "dangling (no defining op, not a block arg)")
        elif d.id not in pos:
            errors.append(f"program output {k}: defined by erased op "
                          f"{d.id} (dangling)")

    errors.extend(_check_types(program, ops))

    _metrics.counter("ir.verify.runs")
    if errors:
        _metrics.counter("ir.verify.violations", len(errors))
    return errors


def _check_types(program: Program, ops) -> List[str]:
    """Re-abstract-eval each primitive-bound op on its operand types and
    compare against the declared result types. Primitives that refuse
    abstract evaluation outside a trace (e.g. ones needing concrete
    params) are skipped, not failed."""
    import jax
    import numpy as np

    errors: List[str] = []
    for op in ops:
        if op.name == CONSTANT_OP or op.id not in program.op_bind:
            continue
        prim, params = program.op_bind[op.id]
        try:
            in_sds = [jax.ShapeDtypeStruct(o.type.shape, np.dtype(o.type.dtype))
                      for o in op.operands]
        except Exception:
            continue  # extended/dynamic dtype — outside np coverage

        def f(*xs, _prim=prim, _params=params):
            subfuns, bind_params = _prim.get_bind_params(dict(_params))
            return _prim.bind(*subfuns, *xs, **bind_params)

        try:
            out = jax.eval_shape(f, *in_sds)
        except Exception:
            continue  # primitive needs trace context — skip, don't fail
        outs = list(out) if prim.multiple_results else [out]
        results = op.results
        if len(outs) != len(results):
            errors.append(
                f"op {op.id} '{op.name}': declares {len(results)} results "
                f"but primitive abstract-evals to {len(outs)}")
            continue
        for k, (sds, res) in enumerate(zip(outs, results)):
            declared = res.type
            try:
                decl_dtype = np.dtype(declared.dtype)
            except Exception:
                continue
            if (tuple(sds.shape) != tuple(declared.shape)
                    or np.dtype(sds.dtype) != decl_dtype):
                errors.append(
                    f"op {op.id} '{op.name}' result {k}: declared "
                    f"{_type_str(declared)} but abstract eval gives "
                    f"{sds.dtype}{list(sds.shape)} (type disagreement)")
    return errors
