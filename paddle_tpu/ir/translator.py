"""Static Program -> IR translation (the reference's ProgramTranslator into
paddle/ir — fluid/ir_adaptor/translator/, program_translator.cc).

A captured static.Program is a linear list of op nodes over tensor ids; this
lifts it into the IR so the pass pipeline applies: DCE strips captured ops
that don't feed the fetch targets (static capture records EVERYTHING executed
under the program guard), CSE merges repeated subgraphs, and the result
re-emits as one jit-compilable callable — the analog of the reference's
Program -> new-IR -> optimized-program flow.

Scope: forward (inference) programs — _OpNode chains. Grad/optimizer nodes
(append_backward products) are higher-order replay nodes, not dataflow ops;
translate the forward slice and differentiate the re-emitted callable with
jax.grad instead (same division the reference draws between the translator
and the autodiff pass).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from .core import Program as IrProgram
from .core import Value


def translate_static(static_program, fetch_vars: Sequence,
                     feed_vars: Optional[Sequence] = None) -> IrProgram:
    """Translate a paddle_tpu.static.Program into an ir.Program.

    feed_vars: placeholder Tensors that become IR block arguments (defaults
    to every placeholder of the program, in insertion order).
    fetch_vars: Tensors whose values become the IR outputs.
    Captured non-feed tensors (parameters, eagerly computed values) enter as
    builtin.constant ops.
    """
    from ..static.program import _OpNode

    prog = IrProgram()
    feed_vars = list(feed_vars) if feed_vars is not None \
        else list(static_program.placeholders.values())
    env: Dict[int, Value] = {}
    for t in feed_vars:
        v = t._value
        env[id(t)] = prog.add_input(prog.ctx.tensor_type(str(v.dtype), v.shape))

    unfed_placeholder_ops: Dict[int, str] = {}  # const op id -> placeholder name

    def value_of(tid: int) -> Value:
        got = env.get(tid)
        if got is None:  # captured tensor: parameter or eager intermediate
            t = static_program.tensors[tid]
            op = prog.add_constant(t._value)
            if getattr(t, "_is_placeholder", False):
                # tolerated only if dead wrt the fetches (checked below)
                unfed_placeholder_ops[op.id] = getattr(t, "name", str(tid))
            got = op.result(0)
            env[tid] = got
        return got

    for node in static_program.nodes:
        if not isinstance(node, _OpNode):
            raise NotImplementedError(
                f"translate_static covers forward programs; found a "
                f"{type(node).__name__} (use jax.grad on the re-emitted "
                f"callable for gradients)")
        operands = [value_of(tid) for tid in node.in_ids]
        result_types = []
        for tid in node.out_ids:
            ov = static_program.tensors[tid]._value
            result_types.append(prog.ctx.tensor_type(str(ov.dtype), ov.shape))
        op = prog.create_op(f"pd.{node.op_name}", operands, result_types,
                            attrs={"fn": node.fn})  # identity token for CSE
        prog.op_fns[op.id] = node.fn
        for tid, res in zip(node.out_ids, op.results):
            env[tid] = res

    prog.set_outputs([value_of(id(t)) for t in fetch_vars])
    prog.verify()
    if unfed_placeholder_ops:
        # an unfed placeholder may only appear in dead captured branches
        # (DCE strips those); if it REACHES a fetch target, translation would
        # silently freeze it at its placeholder value — reject instead
        reachable: set = set()
        stack = [v for v in prog.outputs]
        while stack:
            v = stack.pop()
            op = v.defining_op()
            if op is None or op.id in reachable:
                continue
            reachable.add(op.id)
            stack.extend(op.operands)
        hit = [name for op_id, name in unfed_placeholder_ops.items()
               if op_id in reachable]
        if hit:
            raise ValueError(
                f"placeholder(s) {hit!r} are reachable from the fetch targets "
                "but not listed in feed_vars — baking them in as constants "
                "would silently freeze them at zeros")
    return prog
