"""IR core wrappers over the native uniquing store (native/src/ir_core.cc).

Mirrors paddle/ir's object model — IrContext (ir_context.h:34), Dialect
(dialect.h:29), Operation (operation.h:23), Value, Type, Attribute — with the
storage held natively and uniqued, addressed by integer ids across the C ABI.

The program model is a flat jaxpr: ``trace(fn, *args)`` builds a Program from
``jax.make_jaxpr``; ``Program.to_callable()`` re-emits a jittable function by
re-binding each op's JAX primitive. Complex primitive params (sub-jaxprs for
scan/cond bodies, dimension_numbers, ...) stay Python-side in a per-program
side table, mirrored into the native graph as opaque ``py:`` token attributes
so native CSE stays conservative-but-correct.
"""

from __future__ import annotations

import ctypes
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .. import native as _native

_SIMPLE_DTYPES = dict(_native._DTYPE_CODES)
_SIMPLE_DTYPES.update({
    "complex64": 10, "complex128": 11,
    "uint16": 12, "uint32": 13, "uint64": 14,
})
_CODE_TO_DTYPE = {v: k for k, v in _SIMPLE_DTYPES.items()}
_TOKEN_CODE = 98  # jax token / effect values (no dtype)

_bound = False


def _lib():
    global _bound
    lib = _native._load()
    if _bound:
        return lib
    c_i64 = ctypes.c_int64
    c_i32 = ctypes.c_int32
    p_i64 = ctypes.POINTER(c_i64)
    sigs = {
        "ir_ctx_create": (ctypes.c_void_p, []),
        "ir_ctx_destroy": (None, [ctypes.c_void_p]),
        "ir_type_get": (c_i64, [ctypes.c_void_p, c_i32, p_i64, c_i32]),
        "ir_type_dtype": (c_i32, [ctypes.c_void_p, c_i64]),
        "ir_type_ndim": (c_i32, [ctypes.c_void_p, c_i64]),
        "ir_type_shape": (None, [ctypes.c_void_p, c_i64, p_i64]),
        "ir_block_arg": (c_i64, [ctypes.c_void_p, c_i64]),
        "ir_value_def_op": (c_i64, [ctypes.c_void_p, c_i64]),
        "ir_value_def_index": (c_i32, [ctypes.c_void_p, c_i64]),
        "ir_value_type": (c_i64, [ctypes.c_void_p, c_i64]),
        "ir_value_num_uses": (c_i64, [ctypes.c_void_p, c_i64]),
        "ir_num_block_args": (c_i64, [ctypes.c_void_p]),
        "ir_block_arg_at": (c_i64, [ctypes.c_void_p, c_i64]),
        "ir_op_create": (c_i64, [ctypes.c_void_p, ctypes.c_char_p, p_i64, c_i32, p_i64, c_i32, c_i32]),
        "ir_op_result": (c_i64, [ctypes.c_void_p, c_i64, c_i32]),
        "ir_op_name": (ctypes.c_char_p, [ctypes.c_void_p, c_i64]),
        "ir_op_num_operands": (c_i32, [ctypes.c_void_p, c_i64]),
        "ir_op_num_results": (c_i32, [ctypes.c_void_p, c_i64]),
        "ir_op_operand": (c_i64, [ctypes.c_void_p, c_i64, c_i32]),
        "ir_op_side_effect": (c_i32, [ctypes.c_void_p, c_i64]),
        "ir_op_set_operand": (None, [ctypes.c_void_p, c_i64, c_i32, c_i64]),
        "ir_op_move_before": (c_i32, [ctypes.c_void_p, c_i64, c_i64]),
        "ir_op_set_attr_i": (None, [ctypes.c_void_p, c_i64, ctypes.c_char_p, c_i64]),
        "ir_op_set_attr_f": (None, [ctypes.c_void_p, c_i64, ctypes.c_char_p, ctypes.c_double]),
        "ir_op_set_attr_s": (None, [ctypes.c_void_p, c_i64, ctypes.c_char_p, ctypes.c_char_p]),
        "ir_op_set_attr_ia": (None, [ctypes.c_void_p, c_i64, ctypes.c_char_p, p_i64, c_i32]),
        "ir_op_num_attrs": (c_i32, [ctypes.c_void_p, c_i64]),
        "ir_op_attr_key": (ctypes.c_char_p, [ctypes.c_void_p, c_i64, c_i32]),
        "ir_op_attr_tag": (c_i32, [ctypes.c_void_p, c_i64, c_i32]),
        "ir_op_attr_i": (c_i64, [ctypes.c_void_p, c_i64, c_i32]),
        "ir_op_attr_f": (ctypes.c_double, [ctypes.c_void_p, c_i64, c_i32]),
        "ir_op_attr_s": (ctypes.c_char_p, [ctypes.c_void_p, c_i64, c_i32]),
        "ir_op_attr_ia_len": (c_i32, [ctypes.c_void_p, c_i64, c_i32]),
        "ir_op_attr_ia": (None, [ctypes.c_void_p, c_i64, c_i32, p_i64]),
        "ir_num_ops": (c_i64, [ctypes.c_void_p]),
        "ir_op_at": (c_i64, [ctypes.c_void_p, c_i64]),
        "ir_alive_ops": (c_i64, [ctypes.c_void_p, p_i64, c_i64]),
        "ir_set_outputs": (None, [ctypes.c_void_p, p_i64, c_i32]),
        "ir_num_outputs": (c_i32, [ctypes.c_void_p]),
        "ir_output_at": (c_i64, [ctypes.c_void_p, c_i32]),
        "ir_replace_all_uses": (c_i64, [ctypes.c_void_p, c_i64, c_i64]),
        "ir_erase_op": (c_i32, [ctypes.c_void_p, c_i64]),
        "ir_verify": (c_i32, [ctypes.c_void_p]),
        "ir_dce": (c_i64, [ctypes.c_void_p]),
        "ir_cse": (c_i64, [ctypes.c_void_p]),
        "ir_print": (c_i64, [ctypes.c_void_p, ctypes.c_char_p, c_i64]),
    }
    for name, (res, args) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = res
        fn.argtypes = args
    _bound = True
    return lib


class Type:
    """Uniqued ranked tensor type (dtype + static shape)."""

    __slots__ = ("ctx", "id")

    def __init__(self, ctx: "IrContext", tid: int):
        self.ctx, self.id = ctx, tid

    @property
    def dtype(self) -> Optional[str]:
        code = _lib().ir_type_dtype(self.ctx._h, self.id)
        return self.ctx._dyn_codes_rev.get(code, _CODE_TO_DTYPE.get(code))

    @property
    def shape(self) -> Tuple[int, ...]:
        lib = _lib()
        n = lib.ir_type_ndim(self.ctx._h, self.id)
        buf = (ctypes.c_int64 * max(n, 1))()
        if n:
            lib.ir_type_shape(self.ctx._h, self.id, buf)
        return tuple(buf[i] for i in range(n))

    def __eq__(self, other):
        return isinstance(other, Type) and other.ctx is self.ctx and other.id == self.id

    def __hash__(self):
        return hash((id(self.ctx), self.id))

    def __repr__(self):
        return f"tensor<{'x'.join(map(str, self.shape))}x{self.dtype}>"


class Value:
    """SSA value: block argument or op result, with native use counting."""

    __slots__ = ("ctx", "id")

    def __init__(self, ctx: "IrContext", vid: int):
        self.ctx, self.id = ctx, vid

    @property
    def type(self) -> Type:
        return Type(self.ctx, _lib().ir_value_type(self.ctx._h, self.id))

    @property
    def num_uses(self) -> int:
        return _lib().ir_value_num_uses(self.ctx._h, self.id)

    def defining_op(self) -> Optional["Operation"]:
        op = _lib().ir_value_def_op(self.ctx._h, self.id)
        return None if op < 0 else Operation(self.ctx, op)

    @property
    def result_index(self) -> int:
        return _lib().ir_value_def_index(self.ctx._h, self.id)

    def replace_all_uses_with(self, other: "Value") -> int:
        return _lib().ir_replace_all_uses(self.ctx._h, self.id, other.id)

    def __eq__(self, other):
        return isinstance(other, Value) and other.ctx is self.ctx and other.id == self.id

    def __hash__(self):
        return hash((id(self.ctx), self.id))

    def __repr__(self):
        return f"%{self.id}"


class Attribute:
    """Plain attribute view (key → int/float/str/int-list)."""

    __slots__ = ("key", "value")

    def __init__(self, key: str, value: Any):
        self.key, self.value = key, value

    def __repr__(self):
        return f"{self.key}={self.value!r}"


class Operation:
    """One op in program order: interned name, operands, results, attrs."""

    __slots__ = ("ctx", "id")

    def __init__(self, ctx: "IrContext", op_id: int):
        self.ctx, self.id = ctx, op_id

    @property
    def name(self) -> str:
        return _lib().ir_op_name(self.ctx._h, self.id).decode()

    @property
    def operands(self) -> List[Value]:
        lib = _lib()
        return [Value(self.ctx, lib.ir_op_operand(self.ctx._h, self.id, i))
                for i in range(lib.ir_op_num_operands(self.ctx._h, self.id))]

    @property
    def results(self) -> List[Value]:
        lib = _lib()
        return [Value(self.ctx, lib.ir_op_result(self.ctx._h, self.id, i))
                for i in range(lib.ir_op_num_results(self.ctx._h, self.id))]

    def result(self, i: int = 0) -> Value:
        return Value(self.ctx, _lib().ir_op_result(self.ctx._h, self.id, i))

    @property
    def has_side_effect(self) -> bool:
        return bool(_lib().ir_op_side_effect(self.ctx._h, self.id))

    def set_operand(self, i: int, v: Value):
        _lib().ir_op_set_operand(self.ctx._h, self.id, i, v.id)

    def attrs(self) -> Dict[str, Any]:
        lib = _lib()
        out = {}
        for i in range(lib.ir_op_num_attrs(self.ctx._h, self.id)):
            key = lib.ir_op_attr_key(self.ctx._h, self.id, i).decode()
            tag = lib.ir_op_attr_tag(self.ctx._h, self.id, i)
            if tag == 0:
                out[key] = lib.ir_op_attr_i(self.ctx._h, self.id, i)
            elif tag == 1:
                out[key] = lib.ir_op_attr_f(self.ctx._h, self.id, i)
            elif tag == 2:
                out[key] = lib.ir_op_attr_s(self.ctx._h, self.id, i).decode()
            else:
                n = lib.ir_op_attr_ia_len(self.ctx._h, self.id, i)
                buf = (ctypes.c_int64 * max(n, 1))()
                lib.ir_op_attr_ia(self.ctx._h, self.id, i, buf)
                out[key] = [buf[j] for j in range(n)]
        return out

    def erase(self) -> bool:
        return _lib().ir_erase_op(self.ctx._h, self.id) == 0

    def __eq__(self, other):
        return isinstance(other, Operation) and other.ctx is self.ctx and other.id == self.id

    def __hash__(self):
        return hash((id(self.ctx), self.id))

    def __repr__(self):
        return f'<op {self.id} "{self.name}">'


class Dialect:
    """Namespace of op names (builtin./pd./stablehlo. prefixes)."""

    _registry: Dict[str, "Dialect"] = {}

    def __init__(self, name: str):
        self.name = name
        self.ops: List[str] = []
        Dialect._registry[name] = self

    def register_op(self, op_name: str):
        self.ops.append(op_name)

    @classmethod
    def get(cls, name: str) -> "Dialect":
        return cls._registry.get(name) or Dialect(name)


BUILTIN_DIALECT = Dialect("builtin")
PD_DIALECT = Dialect("pd")

CONSTANT_OP = "builtin.constant"


class IrContext:
    """Owns one native uniquing store; all IR objects hang off it."""

    def __init__(self):
        self._h = _lib().ir_ctx_create()
        self._dyn_codes: Dict[str, int] = {}
        self._dyn_codes_rev: Dict[int, str] = {}

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                _lib().ir_ctx_destroy(self._h)
                self._h = None
        except Exception:
            pass

    def _dtype_code(self, name: str) -> int:
        if name in _SIMPLE_DTYPES:
            return _SIMPLE_DTYPES[name]
        if name not in self._dyn_codes:
            code = 100 + len(self._dyn_codes)
            self._dyn_codes[name] = code
            self._dyn_codes_rev[code] = name
        return self._dyn_codes[name]

    def tensor_type(self, dtype, shape: Sequence[int]) -> Type:
        code = self._dtype_code(np.dtype(dtype).name if not isinstance(dtype, str) else dtype)
        shape = [int(s) for s in shape]
        arr = (ctypes.c_int64 * max(len(shape), 1))(*shape)
        return Type(self, _lib().ir_type_get(self._h, code, arr, len(shape)))

    def token_type(self) -> Type:
        arr = (ctypes.c_int64 * 1)()
        return Type(self, _lib().ir_type_get(self._h, _TOKEN_CODE, arr, 0))


class Program:
    """A single-block IR function + Python side tables for reconstruction.

    Side tables: ``op_bind[op_id] = (primitive, params)`` for primitive ops,
    ``const_vals[op_id] = ndarray`` for builtin.constant. Input/output pytree
    structure is preserved so the re-emitted callable keeps the original
    signature.
    """

    def __init__(self, ctx: Optional[IrContext] = None):
        self.ctx = ctx or IrContext()
        # block args / outputs live on the native context, so two Programs
        # over one context would interleave inputs and clobber outputs
        if getattr(self.ctx, "_owner", None) is not None:
            raise ValueError("IrContext is already bound to a Program; "
                             "create a fresh context per program")
        self.ctx._owner = True  # sentinel, not self: avoid a ctx<->program
        #                         cycle that would defer native store release
        self.op_bind: Dict[int, Tuple[Any, Dict[str, Any]]] = {}
        self.op_fns: Dict[int, Callable] = {}  # opaque-fn ops (static translator)
        self.const_vals: Dict[int, Any] = {}
        self.in_tree = None
        self.out_tree = None
        self._token_ids: Dict[int, int] = {}
        self._token_objs: List[Any] = []

    # ---- construction ----
    def add_input(self, type_: Type) -> Value:
        return Value(self.ctx, _lib().ir_block_arg(self.ctx._h, type_.id))

    def create_op(self, name: str, operands: Sequence[Value],
                  result_types: Sequence[Type], attrs: Optional[Dict[str, Any]] = None,
                  side_effect: bool = False,
                  before: Optional["Operation"] = None) -> Operation:
        """Create an op; with `before=` it is inserted at that op's program
        position (the pattern-fusion primitive: a replacement op takes the
        matched subgraph's place so def-before-use holds for its users)."""
        h = self.ctx._h
        ops_arr = (ctypes.c_int64 * max(len(operands), 1))(*[v.id for v in operands])
        res_arr = (ctypes.c_int64 * max(len(result_types), 1))(*[t.id for t in result_types])
        op_id = _lib().ir_op_create(h, name.encode(), ops_arr, len(operands),
                                    res_arr, len(result_types), int(side_effect))
        if op_id < 0:
            raise ValueError(f"ir_op_create failed for {name}")
        op = Operation(self.ctx, op_id)
        for k, v in (attrs or {}).items():
            self._set_attr(op_id, k, v)
        if before is not None:
            if _lib().ir_op_move_before(h, op_id, before.id) != 0:
                raise ValueError("ir_op_move_before failed")
        return op

    def _py_token(self, obj: Any) -> int:
        tok = self._token_ids.get(id(obj))
        if tok is None:
            tok = len(self._token_ids)
            self._token_ids[id(obj)] = tok
            self._token_objs.append(obj)  # pin: id() stays unique for the
        return tok                        # program's lifetime

    def _set_attr(self, op_id: int, key: str, v: Any):
        lib, h = _lib(), self.ctx._h
        if isinstance(v, (bool, int, np.integer)):
            lib.ir_op_set_attr_i(h, op_id, key.encode(), int(v))
        elif isinstance(v, (float, np.floating)):
            lib.ir_op_set_attr_f(h, op_id, key.encode(), float(v))
        elif isinstance(v, str):
            lib.ir_op_set_attr_s(h, op_id, key.encode(), v.encode())
        elif isinstance(v, (tuple, list)) and all(isinstance(x, (bool, int, np.integer)) for x in v):
            arr = (ctypes.c_int64 * max(len(v), 1))(*[int(x) for x in v])
            lib.ir_op_set_attr_ia(h, op_id, key.encode(), arr, len(v))
        else:
            # opaque: conservative identity token (same object <=> equal)
            lib.ir_op_set_attr_i(h, op_id, f"py:{key}".encode(), self._py_token(v))

    def add_constant(self, value, before: Optional[Operation] = None) -> Operation:
        arr = np.asarray(value)
        t = self.ctx.tensor_type(arr.dtype.name, arr.shape)
        attrs: Dict[str, Any] = {}
        if arr.ndim == 0 and arr.dtype.kind in "ifb":
            attrs["value"] = arr.item()  # scalars unique natively -> CSE merges
        else:
            attrs["value_token"] = self._py_token(value)
        # `before=` keeps def-before-use in program order when a pass feeds
        # the constant to an already-existing op (to_callable hoists all
        # constants so re-emission would mask the violation; the structural
        # verifier does not)
        op = self.create_op(CONSTANT_OP, [], [t], attrs, before=before)
        self.const_vals[op.id] = value
        return op

    def set_outputs(self, values: Sequence[Value]):
        arr = (ctypes.c_int64 * max(len(values), 1))(*[v.id for v in values])
        _lib().ir_set_outputs(self.ctx._h, arr, len(values))

    # ---- inspection ----
    @property
    def inputs(self) -> List[Value]:
        lib, h = _lib(), self.ctx._h
        return [Value(self.ctx, lib.ir_block_arg_at(h, i))
                for i in range(lib.ir_num_block_args(h))]

    @property
    def outputs(self) -> List[Value]:
        lib, h = _lib(), self.ctx._h
        return [Value(self.ctx, lib.ir_output_at(h, i))
                for i in range(lib.ir_num_outputs(h))]

    def ops(self) -> List[Operation]:
        lib, h = _lib(), self.ctx._h
        cap = lib.ir_num_ops(h)
        buf = (ctypes.c_int64 * max(cap, 1))()
        n = lib.ir_alive_ops(h, buf, cap)
        return [Operation(self.ctx, buf[i]) for i in range(n)]

    def __len__(self):
        return int(_lib().ir_num_ops(self.ctx._h))

    def verify(self):
        rc = _lib().ir_verify(self.ctx._h)
        if rc != 0:
            raise ValueError(f"IR verification failed (code {rc})")

    def __str__(self):
        lib, h = _lib(), self.ctx._h
        n = lib.ir_print(h, None, 0)
        buf = ctypes.create_string_buffer(n + 1)
        lib.ir_print(h, buf, n + 1)
        return buf.value.decode()

    # ---- native passes ----
    def dce(self) -> int:
        return int(_lib().ir_dce(self.ctx._h))

    def cse(self) -> int:
        return int(_lib().ir_cse(self.ctx._h))

    # ---- re-emission ----
    def to_callable(self) -> Callable:
        """Re-emit as a Python callable that re-binds each primitive.

        The result traces cleanly under jax.jit — the executor pipeline is
        XLA itself (SURVEY §3.3 TPU note).
        """
        self.verify()
        # constants are position-free (hoisted first); other ops keep
        # program order, which the verifier guarantees is def-before-use
        plan = []  # (kind, op_id, operand_vids, result_vids, payload)
        for op in self.ops():
            if op.name == CONSTANT_OP:
                plan.append(("const", op.id, (), [r.id for r in op.results],
                             self.const_vals[op.id]))
        for op in self.ops():
            if op.name == CONSTANT_OP:
                continue
            if op.id in self.op_fns:
                plan.append(("fn", op.id, tuple(o.id for o in op.operands),
                             [r.id for r in op.results], self.op_fns[op.id]))
            elif op.id in self.op_bind:
                prim, params = self.op_bind[op.id]
                plan.append(("bind", op.id, tuple(o.id for o in op.operands),
                             [r.id for r in op.results], (prim, params)))
            else:
                raise ValueError(
                    f"op {op.name} (id {op.id}) has no JAX primitive "
                    "binding; re-emission requires ops created via "
                    "from_jaxpr/trace or translate_static (manually built "
                    "ops must be rewritten away by passes first)")
        in_vids = [v.id for v in self.inputs]
        out_vids = [v.id for v in self.outputs]
        in_tree, out_tree = self.in_tree, self.out_tree

        def run(*args, **kwargs):
            if in_tree is not None:
                flat, tree = jax.tree_util.tree_flatten((args, kwargs))
                if tree != in_tree:
                    raise TypeError("argument structure does not match traced program")
            else:
                flat = list(args)
            env: Dict[int, Any] = dict(zip(in_vids, flat))
            for kind, _oid, operand_ids, result_ids, payload in plan:
                if kind == "const":
                    env[result_ids[0]] = payload
                    continue
                if kind == "fn":
                    outs = payload(*(env[i] for i in operand_ids))
                    leaves = jax.tree_util.tree_leaves(outs)
                    for rid, v in zip(result_ids, leaves):
                        env[rid] = v
                    continue
                prim, params = payload
                args_in = [env[i] for i in operand_ids]
                # get_bind_params reconstructs positional sub-functions for
                # higher-order primitives (custom_jvp/vjp, scan, pjit) exactly
                # as jax.core.eval_jaxpr does — custom grad rules survive
                subfuns, bind_params = prim.get_bind_params(params)
                vals = prim.bind(*subfuns, *args_in, **bind_params)
                if prim.multiple_results:
                    for rid, v in zip(result_ids, vals):
                        env[rid] = v
                else:
                    env[result_ids[0]] = vals
            outs = [env[i] for i in out_vids]
            if out_tree is not None:
                return jax.tree_util.tree_unflatten(out_tree, outs)
            return tuple(outs)

        return run


def _aval_type(ctx: IrContext, aval) -> Type:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return ctx.token_type()
    return ctx.tensor_type(str(dtype), shape)


def from_jaxpr(closed_jaxpr, in_tree=None, out_tree=None) -> Program:
    """Import a ClosedJaxpr into a fresh Program (jaxpr -> IR translation —
    the analog of the reference's program_translator into paddle/ir)."""
    prog = Program()
    prog.in_tree, prog.out_tree = in_tree, out_tree
    jaxpr = closed_jaxpr.jaxpr
    env: Dict[Any, Value] = {}
    for var in jaxpr.invars:
        env[var] = prog.add_input(_aval_type(prog.ctx, var.aval))
    for var, const in zip(jaxpr.constvars, closed_jaxpr.consts):
        env[var] = prog.add_constant(const).result(0)

    from jax.extend.core import Literal as literal_cls
    for eqn in jaxpr.eqns:
        operands = []
        for iv in eqn.invars:
            if isinstance(iv, literal_cls):
                operands.append(prog.add_constant(iv.val).result(0))
            else:
                operands.append(env[iv])
        result_types = [_aval_type(prog.ctx, ov.aval) for ov in eqn.outvars]
        side_effect = bool(getattr(eqn, "effects", None))
        name = eqn.primitive.name
        full_name = name if "." in name else f"pd.{name}"
        op = prog.create_op(full_name, operands, result_types,
                            attrs=dict(eqn.params), side_effect=side_effect)
        prog.op_bind[op.id] = (eqn.primitive, dict(eqn.params))
        for ov, res in zip(eqn.outvars, op.results):
            env[ov] = res

    prog.set_outputs([env[ov] if not isinstance(ov, literal_cls)
                      else prog.add_constant(ov.val).result(0)
                      for ov in jaxpr.outvars])
    prog.verify()
    return prog


def trace(fn: Callable, *args, **kwargs) -> Program:
    """Trace ``fn`` on example args into a Program (preserving pytrees)."""
    flat_args, in_tree = jax.tree_util.tree_flatten((args, kwargs))
    store = {}

    def flat_fn(*flat):
        a, k = jax.tree_util.tree_unflatten(in_tree, flat)
        out = fn(*a, **k)
        flat_out, out_tree = jax.tree_util.tree_flatten(out)
        store["out_tree"] = out_tree
        return flat_out

    closed = jax.make_jaxpr(flat_fn)(*flat_args)
    return from_jaxpr(closed, in_tree=in_tree, out_tree=store["out_tree"])
