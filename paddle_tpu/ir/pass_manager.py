"""Pass infrastructure: Pass / PassRegistry / PassManager.

Reference surface: fluid/framework/ir/pass.h (Pass::Apply), pass registry
macros (REGISTER_PASS), and python/paddle's PassManager over the new IR.
Passes mutate a Program in place and report a change count; the manager runs
its pipeline to a fixed point (bounded rounds), matching how the reference's
analysis pipeline re-runs dependent passes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type, Union

from ..observability import metrics as _metrics
from ..observability.tracing import span as _span
from .core import Program
from .verifier import (PassVerificationError, verification_enabled,
                       verify_structure)


class Pass:
    """Base pass: subclass and implement run(program) -> int (num changes)."""

    name = "pass"

    def run(self, program: Program) -> int:
        raise NotImplementedError

    def __call__(self, program: Program) -> int:
        n = self.run(program)
        program.verify()
        # structural verifier (def-before-use, dangling values, type
        # agreement) — flag-gated, on by default under pytest
        if verification_enabled():
            errs = verify_structure(program)
            if errs:
                detail = "\n  ".join(errs[:8])
                raise PassVerificationError(
                    f"pass '{self.name}' left the program structurally "
                    f"invalid ({len(errs)} violation(s)):\n  {detail}")
        return n


class PassRegistry:
    _passes: Dict[str, Type[Pass]] = {}

    @classmethod
    def register(cls, pass_cls: Type[Pass]):
        cls._passes[pass_cls.name] = pass_cls
        return pass_cls

    @classmethod
    def get(cls, name: str) -> Pass:
        if name not in cls._passes:
            raise KeyError(f"unknown pass '{name}'; registered: {sorted(cls._passes)}")
        return cls._passes[name]()

    @classmethod
    def names(cls) -> List[str]:
        return sorted(cls._passes)


def register_pass(pass_cls: Type[Pass]):
    """Decorator: REGISTER_PASS analog."""
    return PassRegistry.register(pass_cls)


DEFAULT_PIPELINE = ["algebraic_simplify", "constant_folding", "cse", "dce"]
# Order constraints: multihead before fc (the QKV projections must still be
# raw dot+add when the attention pattern anchors); gelu before fc (fc
# absorbs pd.gelu as its activation); layer_norm before embedding_eltwise
# (which anchors on pd.layer_norm); affine/conv_bn folds before fc (folding
# a BN scale INTO the matmul weights beats wrapping the matmul in a fused
# op, so fc must not consume those matmuls first).
INFERENCE_PIPELINE = ["delete_quant_dequant", "dropout_eliminate",
                      "multihead_matmul_fuse", "gelu_fuse",
                      "layer_norm_fuse", "embedding_eltwise_layernorm_fuse",
                      "skip_layernorm_fuse",
                      "algebraic_simplify", "constant_folding",
                      "affine_chain_collapse", "conv_bn_fuse",
                      "fc_fuse", "cse", "dce"]


class PassManager:
    """Runs a pipeline of passes to a fixed point (<= max_rounds)."""

    def __init__(self, passes: Optional[Sequence[Union[str, Pass]]] = None,
                 max_rounds: int = 4):
        if passes is None:
            passes = DEFAULT_PIPELINE
        self.passes: List[Pass] = [PassRegistry.get(p) if isinstance(p, str) else p
                                   for p in passes]
        self.max_rounds = max_rounds
        self.stats: Dict[str, int] = {}

    def run(self, program: Program) -> Dict[str, int]:
        self.stats = {p.name: 0 for p in self.passes}
        for _ in range(self.max_rounds):
            changed = 0
            for p in self.passes:
                # ir.pass.seconds{pass=...} histogram via the span tracer
                with _span("ir.pass", **{"pass": p.name}):
                    n = p(program)
                if n:
                    _metrics.counter("ir.pass.rewrites", n,
                                     **{"pass": p.name})
                else:
                    _metrics.counter("ir.pass.no_change", 1,
                                     **{"pass": p.name})
                self.stats[p.name] += n
                changed += n
            _metrics.counter("ir.pass_manager.rounds")
            if not changed:
                break
        return self.stats
