"""paddle.Model — the hapi high-level trainer (hapi/model.py:1018 analog).

The reference dispatches fit through DynamicGraphAdapter (eager) or
StaticGraphAdapter (program). TPU-native there is one adapter: the eager
tape drives `loss.backward()` + `optimizer.step()` per batch, and everything
under it is jit-compiled op-level; the jitted whole-step path lives in
fleet.utils.ShardedTrainStep / auto_parallel.Engine for the perf-critical
loops. hapi's value is the loop + callbacks + metrics contract, kept intact.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..metric.metrics import Metric
from .callbacks import Callback, CallbackList, ModelCheckpoint, ProgBarLogger


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False
        self._save_dir = None

    # ---------- setup ----------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss must be a callable or nn.Layer")
        self._loss = loss
        for m in _to_list(metrics):
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle.metric.Metric")
        self._metrics = _to_list(metrics)
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    # ---------- batch-level ----------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        outputs = self.network(*[_as_tensor(v) for v in inputs])
        losses = self._loss(*(_to_list(outputs) + [_as_tensor(v) for v in labels]))
        losses = _to_list(losses)
        total = losses[0]
        for extra in losses[1:]:
            total = total + extra
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            out0 = _to_list(outputs)[0]
            metrics.append(m.update(*_to_list(m.compute(out0, *[_as_tensor(v) for v in labels]))))
        return ([float(_np(l)) for l in losses], metrics) if metrics else [float(_np(l)) for l in losses]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        outputs = self.network(*[_as_tensor(v) for v in inputs])
        losses = []
        if self._loss is not None and labels:
            losses = [float(_np(l)) for l in _to_list(self._loss(*(_to_list(outputs) + [_as_tensor(v) for v in labels])))]
        metrics = []
        for m in self._metrics:
            out0 = _to_list(outputs)[0]
            metrics.append(m.update(*_to_list(m.compute(out0, *[_as_tensor(v) for v in labels]))))
        return (losses, metrics) if metrics else losses

    def predict_batch(self, inputs):
        self.network.eval()
        outputs = self.network(*[_as_tensor(v) for v in _to_list(inputs)])
        return [_np(o) for o in _to_list(outputs)]

    # ---------- loops ----------
    def _loader(self, data, batch_size, shuffle, num_workers):
        from ..io import DataLoader, Dataset

        if isinstance(data, DataLoader) or (hasattr(data, "__iter__") and not isinstance(data, Dataset)):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle, num_workers=num_workers, drop_last=False)

    def fit(
        self,
        train_data=None,
        eval_data=None,
        batch_size=1,
        epochs=1,
        eval_freq=1,
        log_freq=10,
        save_dir=None,
        save_freq=1,
        verbose=2,
        drop_last=False,
        shuffle=True,
        num_workers=0,
        callbacks=None,
        accumulate_grad_batches=1,
        num_iters=None,
    ):
        assert train_data is not None
        self._save_dir = save_dir
        loader = self._loader(train_data, batch_size, shuffle, num_workers)
        # exposed so ModelCheckpoint(save_steps=N) can fold the loader's
        # position into the step checkpoint (TrainState.data_position)
        self._train_loader = loader
        cbks = CallbackList(_to_list(callbacks))
        if verbose:
            cbks.append(ProgBarLogger(log_freq, verbose=verbose))
        if save_dir:
            cbks.append(ModelCheckpoint(save_freq, save_dir))
        cbks.set_model(self)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks.set_params({"epochs": epochs, "steps": steps, "verbose": verbose, "metrics": self._metrics_names()})

        self.stop_training = False
        cbks.on_train_begin()
        logs = {}
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            if hasattr(loader, "set_epoch"):
                loader.set_epoch(epoch)  # epoch-deterministic reshuffle
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(loader):
                if num_iters is not None and step >= num_iters:
                    break
                inputs, labels = self._split(batch)
                cbks.on_train_batch_begin(step)
                res = self.train_batch(inputs, labels, update=(step + 1) % accumulate_grad_batches == 0)
                logs = self._pack_logs(res)
                cbks.on_train_batch_end(step, logs)
            cbks.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size, verbose=0, callbacks=cbks, _nested=True)
        cbks.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0, callbacks=None, num_iters=None, _nested=False):
        loader = self._loader(eval_data, batch_size, False, num_workers)
        cbks = callbacks if _nested else CallbackList(_to_list(callbacks))
        if not _nested:
            cbks.set_model(self)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        losses = []
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            inputs, labels = self._split(batch)
            cbks.on_eval_batch_begin(step)
            res = self.eval_batch(inputs, labels)
            ls = res[0] if isinstance(res, tuple) else res
            if ls:
                losses.append(ls[0] if isinstance(ls, list) else ls)
            cbks.on_eval_batch_end(step, self._pack_logs(res, prefix="eval_"))
        logs = {}
        if losses:
            logs["eval_loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[f"eval_{_name_of(m)}"] = m.accumulate()
            logs[_name_of(m)] = m.accumulate()
        cbks.on_eval_end(logs)
        if verbose:
            print("Eval:", logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False, num_workers)
        cbks = CallbackList(_to_list(callbacks))
        cbks.set_model(self)
        cbks.on_predict_begin()
        outs = []
        for step, batch in enumerate(loader):
            inputs, _ = self._split(batch, labeled=False)
            cbks.on_predict_batch_begin(step)
            outs.append(self.predict_batch(inputs))
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        n_out = len(outs[0]) if outs else 0
        grouped = [[o[i] for o in outs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g, axis=0) for g in grouped]
        return grouped

    # ---------- persistence ----------
    def save(self, path, training=True):
        from ..framework import io as fio

        if training:
            state = {"model": self.network.state_dict()}
            if self._optimizer is not None:
                state["optimizer"] = self._optimizer.state_dict()
            fio.save(state, path + ".pdparams")
        else:
            from .. import jit

            jit.save(self.network, path, input_spec=self._inputs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import io as fio

        state = fio.load(path + ".pdparams")
        self.network.set_state_dict(state["model"] if "model" in state else state)
        if not reset_optimizer and self._optimizer is not None and "optimizer" in state:
            self._optimizer.set_state_dict(state["optimizer"])
        return self

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtypes=dtype)

    # ---------- helpers ----------
    def _metrics_names(self):
        return ["loss"] + [_name_of(m) for m in self._metrics]

    def _split(self, batch, labeled=True):
        items = list(batch) if isinstance(batch, (list, tuple)) else [batch]
        if len(items) == 1:
            return items, []
        if not labeled and self._loss is None and not self._labels:
            return items, []  # genuinely unlabeled multi-input batch
        n_in = len(self._inputs) if self._inputs else max(1, len(items) - (len(self._labels) if self._labels else 1))
        return items[:n_in], items[n_in:]

    def _pack_logs(self, res, prefix=""):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
        else:
            losses, metrics = res, []
        if losses:
            logs[prefix + "loss"] = losses[0] if isinstance(losses, list) else losses
        for m, val in zip(self._metrics, metrics):
            logs[prefix + _name_of(m)] = val
        return logs


def _name_of(m):
    n = m.name()
    return n if isinstance(n, str) else str(n)


def _as_tensor(v):
    return v if isinstance(v, Tensor) else Tensor(np.asarray(v))


def summary(net, input_size=None, dtypes=None, input=None):
    """paddle.summary: parameter table + counts (hapi/model_summary analog)."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        if p is None:
            continue
        n = int(np.prod(p.shape)) if len(p.shape) else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, list(p.shape), n))
    width = max([len(r[0]) for r in rows], default=10) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':>12}"]
    lines.append("-" * (width + 32))
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(shape):<20}{n:>12,}")
    lines.append("-" * (width + 32))
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    lines.append(f"Non-trainable params: {total - trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
