"""hapi callbacks (python/paddle/hapi/callbacks.py analog): the training-loop
event hooks Model.fit drives. Same event order as the reference:
train_begin -> (epoch_begin -> [batch_begin, batch_end]* -> epoch_end)* ->
train_end, with eval_* nested at eval points."""

from __future__ import annotations

import numbers
import os
import time
from typing import List, Optional


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    # train
    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    # eval
    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    # predict
    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):

            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch progress/metric logger (reference prints a progbar; here a
    compact line every log_freq steps)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                parts.append(f"{k}: {v:.4f}")
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            total = f"/{self.steps}" if self.steps else ""
            print(f"Epoch {self.epoch}: step {step}{total} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print(f"Epoch {epoch} done in {time.time() - self._t0:.2f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Epoch-granular model.save (the reference behavior, default) plus
    step-granular fault-tolerant checkpointing: with ``save_steps=N`` the
    full train state (model + optimizer state_dicts) is saved every N train
    batches through ``paddle_tpu.checkpoint.CheckpointManager`` — async
    sharded write, atomic COMMIT, keep_last_n GC — under
    ``<save_dir>/steps/``. Resume with
    ``CheckpointManager(f"{save_dir}/steps").restore()``."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None,
                 save_steps: Optional[int] = None, keep_last_n: Optional[int] = 3):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.save_steps = save_steps
        self.keep_last_n = keep_last_n
        self._manager = None
        self._global_step = 0

    def _collect_state(self):
        from ..data.protocol import iterator_state

        state = {"model": self.model.network.state_dict()}
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and hasattr(opt, "state_dict"):
            state["optimizer"] = opt.state_dict()
        # input-pipeline position (DataLoader / DataPipeline state): restore
        # it to resume mid-epoch without replaying consumed batches
        pos = iterator_state(getattr(self.model, "_train_loader", None))
        if pos is not None:
            state["data_position"] = pos
        return state

    def on_train_begin(self, logs=None):
        if self.save_steps and self.save_dir and self._manager is None:
            from ..checkpoint import CheckpointManager

            self._manager = CheckpointManager(
                os.path.join(self.save_dir, "steps"),
                keep_last_n=self.keep_last_n, async_=True)

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        if (self._manager is not None and self.model is not None
                and self._global_step % self.save_steps == 0):
            self._manager.save(self._global_step, self._collect_state(),
                               force=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self._manager is not None:
            self._manager.wait_until_finished()  # surface async failures
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step or by_epoch)."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None) if self.model else None
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch and self._sched() is not None:
            self._sched().step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and self._sched() is not None:
            self._sched().step()


class EarlyStopping(Callback):
    def __init__(
        self,
        monitor: str = "loss",
        mode: str = "auto",
        patience: int = 0,
        verbose: int = 1,
        min_delta: float = 0.0,
        baseline=None,
        save_best_model: bool = True,
    ):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.reset()

    def reset(self):
        import numpy as np

        self.wait = 0
        self.stopped_epoch = 0
        self.best = -float("inf") if self.mode == "max" else float("inf")
        if self.baseline is not None:
            self.best = self.baseline
        self._np = np

    def _improved(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_train_begin(self, logs=None):
        self.reset()

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._improved(float(cur)):
            self.best = float(cur)
            self.wait = 0
            if self.save_best_model and self.model is not None and getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                if self.model is not None:
                    self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: {self.monitor} did not improve for {self.wait} evals")


class VisualDL(Callback):
    """Scalar logger (VisualDL writer analog): appends metric scalars to a
    jsonl file under log_dir — no visualdl dependency in this environment."""

    def __init__(self, log_dir: str = "./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        import json

        os.makedirs(self.log_dir, exist_ok=True)
        rec = {"tag": tag, "step": self._step}
        rec.update({k: float(v) for k, v in (logs or {}).items() if isinstance(v, numbers.Number)})
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)
