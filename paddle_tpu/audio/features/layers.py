"""Audio feature layers (reference python/paddle/audio/features/layers.py).

Each layer precomputes its static operator (window, fbank, DCT) at build time
and runs a pure jnp pipeline in forward, so a feature extractor inside a
jitted data/compute graph fuses into the surrounding XLA program.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from ..functional import compute_fbank_matrix, create_dct, get_window, power_to_db


def _stft_power(x, n_fft, hop_length, win_length, window_t, center, pad_mode, power):
    from ...signal import stft

    spec = stft(
        x if isinstance(x, Tensor) else Tensor(x),
        n_fft=n_fft,
        hop_length=hop_length,
        win_length=win_length,
        window=window_t,
        center=center,
        pad_mode=pad_mode,
    )
    mag = jnp.abs(spec._value)
    if power == 1.0:
        return mag
    return mag**power


class Spectrogram(Layer):
    """STFT magnitude^power [.., n_fft//2+1, frames] (layers.py:24)."""

    def __init__(
        self,
        n_fft: int = 512,
        hop_length: Optional[int] = 512,
        win_length: Optional[int] = None,
        window: str = "hann",
        power: float = 1.0,
        center: bool = True,
        pad_mode: str = "reflect",
        dtype: str = "float32",
    ):
        super().__init__()
        if win_length is None:
            win_length = n_fft
        self.n_fft = n_fft
        self.hop_length = hop_length if hop_length is not None else win_length // 4
        self.win_length = win_length
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = get_window(window, win_length, fftbins=True, dtype=dtype)

    def forward(self, x):
        return Tensor(
            _stft_power(x, self.n_fft, self.hop_length, self.win_length, self.fft_window, self.center, self.pad_mode, self.power)
        )


class MelSpectrogram(Layer):
    """Mel-projected power spectrogram (layers.py:106)."""

    def __init__(
        self,
        sr: int = 22050,
        n_fft: int = 512,
        hop_length: Optional[int] = 512,
        win_length: Optional[int] = None,
        window: str = "hann",
        power: float = 2.0,
        center: bool = True,
        pad_mode: str = "reflect",
        n_mels: int = 64,
        f_min: float = 50.0,
        f_max: Optional[float] = None,
        htk: bool = False,
        norm: Union[str, float] = "slaney",
        dtype: str = "float32",
    ):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window, power, center, pad_mode, dtype)
        self.n_mels = n_mels
        self.fbank_matrix = compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max, htk=htk, norm=norm, dtype=dtype
        )

    def forward(self, x):
        spect = self._spectrogram(x)  # [..., n_bins, frames]
        mel = jnp.matmul(self.fbank_matrix._value, spect._value)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    """dB-scaled mel spectrogram (layers.py:206)."""

    def __init__(
        self,
        sr: int = 22050,
        n_fft: int = 512,
        hop_length: Optional[int] = 512,
        win_length: Optional[int] = None,
        window: str = "hann",
        power: float = 2.0,
        center: bool = True,
        pad_mode: str = "reflect",
        n_mels: int = 64,
        f_min: float = 50.0,
        f_max: Optional[float] = None,
        htk: bool = False,
        norm: Union[str, float] = "slaney",
        ref_value: float = 1.0,
        amin: float = 1e-10,
        top_db: Optional[float] = None,
        dtype: str = "float32",
    ):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center, pad_mode, n_mels, f_min, f_max, htk, norm, dtype
        )
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return power_to_db(mel, ref_value=self.ref_value, amin=self.amin, top_db=self.top_db)


class MFCC(Layer):
    """Mel-frequency cepstral coefficients (layers.py:309)."""

    def __init__(
        self,
        sr: int = 22050,
        n_mfcc: int = 40,
        norm: str = "ortho",
        dtype: str = "float32",
        **melkwargs,
    ):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(sr=sr, dtype=dtype, **melkwargs)
        n_mels = self._log_melspectrogram._melspectrogram.n_mels
        assert n_mfcc <= n_mels, "n_mfcc cannot be larger than n_mels"
        self.dct_matrix = create_dct(n_mfcc=n_mfcc, n_mels=n_mels, norm=norm, dtype=dtype)

    def forward(self, x):
        log_mel = self._log_melspectrogram(x)  # [..., n_mels, frames]
        mfcc = jnp.einsum("...mf,mk->...kf", log_mel._value, self.dct_matrix._value)
        return Tensor(mfcc)
