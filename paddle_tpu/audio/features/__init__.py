"""paddle.audio.features (reference audio/features/__init__.py)."""

from .layers import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram  # noqa: F401

__all__ = ["LogMelSpectrogram", "MelSpectrogram", "MFCC", "Spectrogram"]
