"""Audio datasets (reference audio/datasets/esc50.py, tess.py).

Feature-extracting datasets: each item is (feature, label) where feature is
raw waveform or a configured mel/mfcc feature. Synthetic waveform fallback in
this zero-egress environment; pass archive_path for real data laid out as the
reference expects.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..io import Dataset
from .features import LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram

_FEATURES = {
    "raw": None,
    "spectrogram": Spectrogram,
    "melspectrogram": MelSpectrogram,
    "logmelspectrogram": LogMelSpectrogram,
    "mfcc": MFCC,
}


class AudioClassificationDataset(Dataset):
    """Base: waveform clips + integer labels (audio/datasets/dataset.py)."""

    def __init__(self, files=None, labels=None, feat_type: str = "raw", sample_rate: int = 16000, duration: float = 1.0, n_classes: int = 10, n_synthetic: int = 64, seed: int = 0, **feat_kwargs):
        if feat_type not in _FEATURES:
            raise ValueError(f"feat_type must be one of {sorted(_FEATURES)}")
        self.sample_rate = sample_rate
        n = int(sample_rate * duration)
        if files:
            from .backends import load

            self.waveforms = []
            self.labels = list(labels)
            for f in files:
                wav, _ = load(f)
                self.waveforms.append(np.asarray(wav.numpy())[0][:n])
        else:
            rng = np.random.RandomState(seed)
            self.labels = rng.randint(0, n_classes, size=n_synthetic).tolist()
            t = np.arange(n) / sample_rate
            self.waveforms = [
                (0.5 * np.sin(2 * np.pi * (200 + 100 * l) * t) + 0.05 * rng.randn(n)).astype(np.float32)
                for l in self.labels
            ]
        if _FEATURES[feat_type] is None:
            self._extract = None
        else:
            if feat_type != "spectrogram":  # Spectrogram is sr-agnostic
                feat_kwargs.setdefault("sr", sample_rate)
            self._extract = _FEATURES[feat_type](**feat_kwargs)

    def __getitem__(self, idx):
        wav = self.waveforms[idx]
        if self._extract is not None:
            feat = self._extract(wav[None, :]).numpy()[0]
        else:
            feat = wav
        return feat, np.int64(self.labels[idx])

    def __len__(self):
        return len(self.waveforms)


class ESC50(AudioClassificationDataset):
    """50-class environmental sounds (esc50.py)."""

    def __init__(self, mode: str = "train", split: int = 1, feat_type: str = "raw", archive_path: Optional[str] = None, **kwargs):
        kwargs.setdefault("n_classes", 50)
        kwargs.setdefault("seed", 0 if mode == "train" else 1)
        kwargs.setdefault("sample_rate", 44100)
        files, labels = None, None
        if archive_path and os.path.isdir(archive_path):
            files, labels = self._scan(archive_path, mode, split)
        super().__init__(files=files, labels=labels, feat_type=feat_type, **kwargs)

    @staticmethod
    def _scan(root, mode, split):
        import csv

        files, labels = [], []
        meta = os.path.join(root, "meta", "esc50.csv")
        with open(meta) as f:
            for row in csv.DictReader(f):
                in_fold = int(row["fold"]) == split
                if (mode == "train") != in_fold:
                    files.append(os.path.join(root, "audio", row["filename"]))
                    labels.append(int(row["target"]))
        return files, labels


class TESS(AudioClassificationDataset):
    """7-emotion speech (tess.py)."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, mode: str = "train", n_folds: int = 5, split: int = 1, feat_type: str = "raw", archive_path: Optional[str] = None, **kwargs):
        kwargs.setdefault("n_classes", len(self.EMOTIONS))
        kwargs.setdefault("seed", 0 if mode == "train" else 1)
        kwargs.setdefault("sample_rate", 24414)
        files, labels = None, None
        if archive_path and os.path.isdir(archive_path):
            files, labels = [], []
            for dirpath, _, names in os.walk(archive_path):
                for nm in sorted(names):
                    if nm.endswith(".wav"):
                        emo = nm.rsplit("_", 1)[-1][:-4].lower()
                        if emo in self.EMOTIONS:
                            files.append(os.path.join(dirpath, nm))
                            labels.append(self.EMOTIONS.index(emo))
            fold = np.arange(len(files)) % n_folds + 1
            keep = [(f, l) for f, l, fd in zip(files, labels, fold) if (fd == split) != (mode == "train")]
            files, labels = [f for f, _ in keep], [l for _, l in keep]
        super().__init__(files=files, labels=labels, feat_type=feat_type, **kwargs)
