"""WAV I/O via the stdlib wave module (reference audio/backends/wave_backend.py).

The reference ships this exact fallback backend (no soundfile dependency):
16-bit PCM read/write. API parity: info/load/save.
"""

from __future__ import annotations

import wave
from typing import Optional, Tuple

import numpy as np

from ...core.tensor import Tensor


class AudioInfo:
    def __init__(self, sample_rate: int, num_samples: int, num_channels: int, bits_per_sample: int, encoding: str):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (
            f"AudioInfo(sample_rate={self.sample_rate}, num_samples={self.num_samples}, "
            f"num_channels={self.num_channels}, bits_per_sample={self.bits_per_sample}, encoding={self.encoding})"
        )


def info(filepath: str) -> AudioInfo:
    with wave.open(filepath, "rb") as f:
        return AudioInfo(
            sample_rate=f.getframerate(),
            num_samples=f.getnframes(),
            num_channels=f.getnchannels(),
            bits_per_sample=f.getsampwidth() * 8,
            encoding="PCM_S",
        )


def load(
    filepath: str,
    frame_offset: int = 0,
    num_frames: int = -1,
    normalize: bool = True,
    channels_first: bool = True,
) -> Tuple[Tensor, int]:
    """Returns (waveform [C, N] if channels_first else [N, C], sample_rate)."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        if width != 2:
            raise NotImplementedError("wave backend supports 16-bit PCM only")
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    data = np.frombuffer(raw, dtype=np.int16).reshape(-1, nch)
    if normalize:
        arr = (data / 32768.0).astype(np.float32)
    else:
        arr = data
    if channels_first:
        arr = arr.T
    return Tensor(arr), sr


def save(
    filepath: str,
    src,
    sample_rate: int,
    channels_first: bool = True,
    encoding: Optional[str] = None,
    bits_per_sample: int = 16,
):
    if bits_per_sample != 16:
        raise NotImplementedError("wave backend writes 16-bit PCM only")
    arr = np.asarray(src.numpy() if isinstance(src, Tensor) else src)
    if arr.ndim == 1:
        arr = arr[None, :] if channels_first else arr[:, None]
    if channels_first:
        arr = arr.T  # -> [N, C]
    if arr.dtype.kind == "f":
        arr = np.clip(arr, -1.0, 1.0)
        arr = (arr * 32767.0).astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(2)
        f.setframerate(sample_rate)
        f.writeframes(arr.astype("<i2").tobytes())


def get_current_audio_backend() -> str:
    return "wave_backend"


def list_available_backends():
    return ["wave_backend"]


def set_backend(backend_name: str):
    if backend_name != "wave_backend":
        raise NotImplementedError("only the builtin wave backend is available (zero-egress image)")
