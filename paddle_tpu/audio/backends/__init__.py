"""paddle.audio.backends (reference audio/backends/__init__.py)."""

from .wave_backend import (  # noqa: F401
    AudioInfo,
    get_current_audio_backend,
    info,
    list_available_backends,
    load,
    save,
    set_backend,
)

__all__ = [
    "info",
    "load",
    "save",
    "get_current_audio_backend",
    "list_available_backends",
    "set_backend",
]
