"""Window functions (reference python/paddle/audio/functional/window.py).

scipy.signal.windows-consistent shapes, computed with numpy at layer-build
time (windows are static per layer, so device placement happens once when the
feature layer jits its first call).
"""

from __future__ import annotations

import math
from typing import Tuple, Union

import numpy as np

from ...core.dtype import convert_dtype, to_jax_dtype
from ...core.tensor import Tensor


def _extend(M: int, sym: bool):
    return (M, False) if sym else (M + 1, True)


def _truncate(w, needs_trunc):
    return w[:-1] if needs_trunc else w


def _general_cosine(M, a, sym):
    if M <= 0:
        return np.zeros(0)
    M, needs_trunc = _extend(M, sym)
    fac = np.linspace(-math.pi, math.pi, M)
    w = np.zeros(M)
    for k, coef in enumerate(a):
        w += coef * np.cos(k * fac)
    return _truncate(w, needs_trunc)


def _general_hamming(M, alpha, sym):
    return _general_cosine(M, [alpha, 1.0 - alpha], sym)


def _hamming(M, sym=True):
    return _general_hamming(M, 0.54, sym)


def _hann(M, sym=True):
    return _general_hamming(M, 0.5, sym)


def _blackman(M, sym=True):
    return _general_cosine(M, [0.42, 0.50, 0.08], sym)


def _cosine(M, sym=True):
    if M <= 0:
        return np.zeros(0)
    M, needs_trunc = _extend(M, sym)
    w = np.sin(math.pi / M * (np.arange(0, M) + 0.5))
    return _truncate(w, needs_trunc)


def _triang(M, sym=True):
    if M <= 0:
        return np.zeros(0)
    M, needs_trunc = _extend(M, sym)
    n = np.arange(1, (M + 1) // 2 + 1)
    if M % 2 == 0:
        w = (2 * n - 1.0) / M
        w = np.concatenate([w, w[::-1]])
    else:
        w = 2 * n / (M + 1.0)
        w = np.concatenate([w, w[-2::-1]])
    return _truncate(w, needs_trunc)


def _bohman(M, sym=True):
    if M <= 0:
        return np.zeros(0)
    M, needs_trunc = _extend(M, sym)
    fac = np.abs(np.linspace(-1, 1, M)[1:-1])
    w = (1 - fac) * np.cos(math.pi * fac) + 1.0 / math.pi * np.sin(math.pi * fac)
    w = np.concatenate([[0], w, [0]])
    return _truncate(w, needs_trunc)


def _gaussian(M, std, sym=True):
    if M <= 0:
        return np.zeros(0)
    M, needs_trunc = _extend(M, sym)
    n = np.arange(0, M) - (M - 1.0) / 2.0
    w = np.exp(-(n**2) / (2 * std * std))
    return _truncate(w, needs_trunc)


def _general_gaussian(M, p, sig, sym=True):
    if M <= 0:
        return np.zeros(0)
    M, needs_trunc = _extend(M, sym)
    n = np.arange(0, M) - (M - 1.0) / 2.0
    w = np.exp(-0.5 * np.abs(n / sig) ** (2 * p))
    return _truncate(w, needs_trunc)


def _exponential(M, center=None, tau=1.0, sym=True):
    if sym and center is not None:
        raise ValueError("If sym==True, center must be None.")
    if M <= 0:
        return np.zeros(0)
    M, needs_trunc = _extend(M, sym)
    if center is None:
        center = (M - 1) / 2
    n = np.arange(0, M)
    w = np.exp(-np.abs(n - center) / tau)
    return _truncate(w, needs_trunc)


def _tukey(M, alpha=0.5, sym=True):
    if M <= 0:
        return np.zeros(0)
    if alpha <= 0:
        return np.ones(M)
    if alpha >= 1.0:
        return _hann(M, sym)
    M, needs_trunc = _extend(M, sym)
    n = np.arange(0, M)
    width = int(np.floor(alpha * (M - 1) / 2.0))
    n1, n2, n3 = n[: width + 1], n[width + 1 : M - width - 1], n[M - width - 1 :]
    w1 = 0.5 * (1 + np.cos(math.pi * (-1 + 2.0 * n1 / alpha / (M - 1))))
    w2 = np.ones(n2.shape)
    w3 = 0.5 * (1 + np.cos(math.pi * (-2.0 / alpha + 1 + 2.0 * n3 / alpha / (M - 1))))
    w = np.concatenate([w1, w2, w3])
    return _truncate(w, needs_trunc)


def _taylor(M, nbar=4, sll=30, norm=True, sym=True):
    if M <= 0:
        return np.zeros(0)
    M, needs_trunc = _extend(M, sym)
    B = 10 ** (sll / 20)
    A = math.acosh(B) / math.pi
    s2 = nbar**2 / (A**2 + (nbar - 0.5) ** 2)
    ma = np.arange(1, nbar)
    Fm = np.zeros(nbar - 1)
    signs = np.empty_like(ma)
    signs[::2] = 1
    signs[1::2] = -1
    m2 = ma * ma
    for mi, _ in enumerate(ma):
        numer = signs[mi] * np.prod(1 - m2[mi] / s2 / (A**2 + (ma - 0.5) ** 2))
        denom = 2 * np.prod(1 - m2[mi] / m2[:mi]) * np.prod(1 - m2[mi] / m2[mi + 1 :])
        Fm[mi] = numer / denom

    def W(n):
        return 1 + 2 * np.dot(Fm, np.cos(2 * math.pi * ma[:, None] * (n - M / 2.0 + 0.5) / M))

    w = W(np.arange(0, M))
    if norm:
        w = w / W((M - 1) / 2)
    return _truncate(w, needs_trunc)


_WINDOWS = {
    "hamming": _hamming,
    "hann": _hann,
    "blackman": _blackman,
    "cosine": _cosine,
    "triang": _triang,
    "bohman": _bohman,
    "gaussian": _gaussian,
    "general_gaussian": _general_gaussian,
    "exponential": _exponential,
    "tukey": _tukey,
    "taylor": _taylor,
}


def get_window(window: Union[str, Tuple], win_length: int, fftbins: bool = True, dtype: str = "float64") -> Tensor:
    """scipy-style window dispatch (window.py:335)."""
    sym = not fftbins
    if isinstance(window, tuple):
        name, args = window[0], tuple(window[1:])
    elif isinstance(window, str):
        name, args = window, ()
        if name in ("gaussian", "exponential", "general_gaussian"):
            raise ValueError(f"The '{name}' window needs one or more parameters -- pass a tuple.")
    else:
        raise ValueError(f"The window type {type(window)} is not supported")
    if name not in _WINDOWS:
        raise ValueError(f"Unknown window type: {name}")
    w = _WINDOWS[name](win_length, *args, sym=sym)
    return Tensor(w.astype(np.dtype(str(to_jax_dtype(convert_dtype(dtype))))))
