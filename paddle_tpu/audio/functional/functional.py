"""Audio DSP helpers (reference python/paddle/audio/functional/functional.py).

librosa/slaney-compatible mel math on jnp; everything here is pure and
jit-traceable so feature layers compile into single XLA programs.
"""

from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.dtype import to_jax_dtype, convert_dtype


def _as_array(x):
    return x._value if isinstance(x, Tensor) else x


def hz_to_mel(freq, htk: bool = False):
    """Hz -> mel (functional.py:22). Slaney by default, HTK optional."""
    is_tensor = isinstance(freq, Tensor)
    f = _as_array(freq)
    if htk:
        if is_tensor:
            return Tensor(2595.0 * jnp.log10(1.0 + f / 700.0))
        return 2595.0 * math.log10(1.0 + f / 700.0)
    f_sp = 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / f_sp
    logstep = math.log(6.4) / 27.0
    if is_tensor:
        mels = jnp.where(f >= min_log_hz, min_log_mel + jnp.log(jnp.maximum(f, min_log_hz) / min_log_hz) / logstep, f / f_sp)
        return Tensor(mels)
    if f >= min_log_hz:
        return min_log_mel + math.log(f / min_log_hz) / logstep
    return f / f_sp


def mel_to_hz(mel, htk: bool = False):
    """mel -> Hz (functional.py:78)."""
    is_tensor = isinstance(mel, Tensor)
    m = _as_array(mel)
    if htk:
        if is_tensor:
            return Tensor(700.0 * (10.0 ** (m / 2595.0) - 1.0))
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    f_sp = 200.0 / 3
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / f_sp
    logstep = math.log(6.4) / 27.0
    if is_tensor:
        hz = jnp.where(m >= min_log_mel, min_log_hz * jnp.exp(logstep * (m - min_log_mel)), f_sp * m)
        return Tensor(hz)
    if m >= min_log_mel:
        return min_log_hz * math.exp(logstep * (m - min_log_mel))
    return f_sp * m


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0, f_max: float = 11025.0, htk: bool = False, dtype: str = "float32") -> Tensor:
    """n_mels+2-free center frequencies (functional.py:123)."""
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mels = jnp.linspace(lo, hi, n_mels).astype(to_jax_dtype(convert_dtype(dtype)))
    return mel_to_hz(Tensor(mels), htk)


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float32") -> Tensor:
    """rfft bin centers (functional.py:163)."""
    return Tensor(jnp.linspace(0, float(sr) / 2, 1 + n_fft // 2).astype(to_jax_dtype(convert_dtype(dtype))))


def compute_fbank_matrix(
    sr: int,
    n_fft: int,
    n_mels: int = 64,
    f_min: float = 0.0,
    f_max: Optional[float] = None,
    htk: bool = False,
    norm: Union[str, float] = "slaney",
    dtype: str = "float32",
) -> Tensor:
    """Mel filterbank [n_mels, n_fft//2+1] (functional.py:186)."""
    if f_max is None:
        f_max = float(sr) / 2
    jdt = to_jax_dtype(convert_dtype(dtype))
    fftfreqs = fft_frequencies(sr, n_fft, dtype)._value
    lo, hi = hz_to_mel(f_min, htk), hz_to_mel(f_max, htk)
    mel_f = mel_to_hz(Tensor(jnp.linspace(lo, hi, n_mels + 2)), htk)._value
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]  # [n_mels+2, n_bins]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2 : n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    elif isinstance(norm, (int, float)):
        weights = weights / jnp.maximum(jnp.linalg.norm(weights, ord=norm, axis=-1, keepdims=True), 1e-10)
    return Tensor(weights.astype(jdt))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10, top_db: Optional[float] = 80.0) -> Tensor:
    """Power spectrogram -> dB (functional.py:259)."""
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    if ref_value <= 0:
        raise ValueError("ref_value must be strictly positive")
    x = _as_array(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
    log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho", dtype: str = "float32") -> Tensor:
    """DCT-II matrix [n_mels, n_mfcc] (functional.py:303)."""
    jdt = to_jax_dtype(convert_dtype(dtype))
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[:, None]
    dct = jnp.cos(math.pi / float(n_mels) * (n + 0.5) * k)  # [n_mfcc, n_mels]
    if norm is None:
        dct = dct * 2.0
    else:
        assert norm == "ortho"
        dct = dct * jnp.where(k == 0, math.sqrt(1.0 / (4.0 * n_mels)) * 2, math.sqrt(1.0 / (2.0 * n_mels)) * 2)
    return Tensor(dct.T.astype(jdt))
