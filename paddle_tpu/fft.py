"""paddle.fft (python/paddle/fft.py analog): full FFT family over jnp.fft —
XLA lowers these to the TPU FFT HLO. Norm semantics ("backward"/"ortho"/
"forward") match the reference."""

from __future__ import annotations

import jax.numpy as jnp

from .ops._dispatch import apply, as_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _wrap1(name, fn):
    def op(x, n=None, axis=-1, norm="backward", name_arg=None):
        return apply(name, lambda v: fn(v, n=n, axis=axis, norm=norm), as_tensor(x))

    op.__name__ = name
    return op


def _wrap2(name, fn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name_arg=None):
        return apply(name, lambda v: fn(v, s=s, axes=axes, norm=norm), as_tensor(x))

    op.__name__ = name
    return op


def _wrapn(name, fn):
    def op(x, s=None, axes=None, norm="backward", name_arg=None):
        return apply(name, lambda v: fn(v, s=s, axes=axes, norm=norm), as_tensor(x))

    op.__name__ = name
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)
fft2 = _wrap2("fft2", jnp.fft.fft2)
ifft2 = _wrap2("ifft2", jnp.fft.ifft2)
rfft2 = _wrap2("rfft2", jnp.fft.rfft2)
irfft2 = _wrap2("irfft2", jnp.fft.irfft2)
fftn = _wrapn("fftn", jnp.fft.fftn)
ifftn = _wrapn("ifftn", jnp.fft.ifftn)
rfftn = _wrapn("rfftn", jnp.fft.rfftn)
irfftn = _wrapn("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def fftshift(x, axes=None, name=None):
    return apply("fftshift", lambda v: jnp.fft.fftshift(v, axes=axes), as_tensor(x))


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift", lambda v: jnp.fft.ifftshift(v, axes=axes), as_tensor(x))


def _hfftn_impl(v, s, axes, norm):
    """Hermitian-symmetric last axis -> real output: full FFT on leading axes,
    hfft on the last (reference: python/paddle/fft.py hfftn). When axes is
    None, s pairs with the LAST len(s) axes (numpy/reference convention)."""
    if axes is None:
        axes = tuple(range(v.ndim)) if s is None else tuple(range(v.ndim - len(s), v.ndim))
    axes = tuple(a % v.ndim for a in axes)
    s_map = dict(zip(axes, s)) if s is not None else {}
    for a in axes[:-1]:
        v = jnp.fft.fft(v, n=s_map.get(a), axis=a, norm=norm)
    return jnp.fft.hfft(v, n=s_map.get(axes[-1]), axis=axes[-1], norm=norm)


def _ihfftn_impl(v, s, axes, norm):
    if axes is None:
        axes = tuple(range(v.ndim)) if s is None else tuple(range(v.ndim - len(s), v.ndim))
    axes = tuple(a % v.ndim for a in axes)
    s_map = dict(zip(axes, s)) if s is not None else {}
    v = jnp.fft.ihfft(v, n=s_map.get(axes[-1]), axis=axes[-1], norm=norm)
    for a in axes[:-1]:
        v = jnp.fft.ifft(v, n=s_map.get(a), axis=a, norm=norm)
    return v


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply("hfftn", lambda v: _hfftn_impl(v, s, axes, norm), as_tensor(x))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply("ihfftn", lambda v: _ihfftn_impl(v, s, axes, norm), as_tensor(x))


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply("hfft2", lambda v: _hfftn_impl(v, s, axes, norm), as_tensor(x))


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply("ihfft2", lambda v: _ihfftn_impl(v, s, axes, norm), as_tensor(x))


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
