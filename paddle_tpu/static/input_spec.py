"""InputSpec (python/paddle/static/input.py analog): shape/dtype signature
for program capture. `None` dims become jax.export symbolic dimensions so one
saved program serves any batch size — the dy2static dynamic-shape contract."""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class InputSpec:
    def __init__(self, shape: Sequence[Optional[int]], dtype="float32", name: Optional[str] = None, stop_gradient: bool = True):
        self.shape = tuple(shape)
        self.dtype = str(dtype).replace("paddle.", "")
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), str(tensor.dtype), name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def _np_dtype(self):
        from ..core.dtype import convert_dtype

        try:
            return np.dtype(convert_dtype(self.dtype))
        except Exception:
            return np.dtype(self.dtype)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    def __eq__(self, other):
        return (
            isinstance(other, InputSpec)
            and self.shape == other.shape
            and self.dtype == other.dtype
            and self.name == other.name
        )

    def __hash__(self):
        return hash((self.shape, self.dtype, self.name))
