"""paddle.static: static-graph user API (SURVEY §2.5/§2.7).

Program capture + Executor replay implemented TPU-style in program.py: ops
recorded at the dispatch seam, replayed as a pure function XLA compiles.
InputSpec and inference-model save/load delegate to paddle.jit (jax tracing
IS program capture for deployment).
"""

from ..nn.layer.layers import disable_static, enable_static, in_dynamic_mode  # noqa: F401
from .input_spec import InputSpec  # noqa: F401
from .program import (  # noqa: F401
    BuildStrategy,
    CompiledProgram,
    Executor,
    ExecutionStrategy,
    ExponentialMovingAverage,
    IpuCompiledProgram,
    IpuStrategy,
    Print,
    Program,
    Scope,
    Variable,
    WeightNormParamAttr,
    accuracy,
    append_backward,
    auc,
    cpu_places,
    create_global_var,
    create_parameter,
    ctr_metric_bundle,
    cuda_places,
    data,
    default_main_program,
    default_startup_program,
    deserialize_persistables,
    deserialize_program,
    device_guard,
    exponential_decay,
    global_scope,
    gradients,
    ipu_shard_guard,
    load,
    load_from_file,
    load_program_state,
    name_scope,
    normalize_program,
    program_guard,
    py_func,
    save,
    save_to_file,
    scope_guard,
    serialize_persistables,
    serialize_program,
    set_ipu_shard,
    set_program_state,
    xpu_places,
)
from .program import Executor as _Executor  # noqa: F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kwargs):
    """static.save_inference_model parity: `fetch_vars` must be produced by a
    jit-captured layer; delegates to paddle.jit.save."""
    layer = kwargs.get("layer")
    if layer is None:
        raise NotImplementedError(
            "TPU build has no ProgramDesc serialization; pass layer= (a "
            "paddle.nn.Layer) or use paddle.jit.save directly"
        )
    from .. import jit

    jit.save(layer, path_prefix, input_spec=feed_vars)


def load_inference_model(path_prefix, executor=None, **kwargs):
    from .. import jit

    layer = jit.load(path_prefix)
    in_names = [s["name"] or f"x{i}" for i, s in enumerate(layer._input_specs)]
    return layer, in_names, None


# static-graph layer builders (reference: paddle.static.nn)
from . import nn  # noqa: F401,E402

__all__ = [
    "InputSpec", "save_inference_model", "load_inference_model", "Program",
    "Executor", "program_guard", "data", "append_backward", "gradients",
    "global_scope", "scope_guard", "BuildStrategy", "CompiledProgram",
    "ExecutionStrategy", "name_scope", "program_guard", "WeightNormParamAttr",
    "ExponentialMovingAverage", "default_main_program",
    "default_startup_program", "save", "load", "serialize_program",
    "serialize_persistables", "save_to_file", "deserialize_program",
    "deserialize_persistables", "load_from_file", "normalize_program",
    "load_program_state", "set_program_state", "cpu_places", "cuda_places",
    "xpu_places", "Variable", "create_global_var", "create_parameter",
    "accuracy", "auc", "device_guard", "exponential_decay",
    "ctr_metric_bundle", "Print", "py_func", "ipu_shard_guard",
    "IpuCompiledProgram", "IpuStrategy", "set_ipu_shard",
]
