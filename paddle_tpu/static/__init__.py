"""paddle.static compat surface (SURVEY §2.7 static).

The reference's static graph (Program/Executor) is subsumed by jax tracing:
`paddle.jit.to_static` IS program capture, the HLO module IS the Program.
This package keeps the names user code imports — InputSpec (real), plus
inference-model save/load delegating to paddle.jit.
"""

from .input_spec import InputSpec

__all__ = ["InputSpec", "save_inference_model", "load_inference_model"]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kwargs):
    """static.save_inference_model parity: `fetch_vars` must be produced by a
    jit-captured layer; delegates to paddle.jit.save."""
    layer = kwargs.get("layer")
    if layer is None:
        raise NotImplementedError(
            "TPU build has no Program objects; pass layer= (a paddle.nn.Layer) "
            "or use paddle.jit.save directly"
        )
    from .. import jit

    jit.save(layer, path_prefix, input_spec=feed_vars)


def load_inference_model(path_prefix, executor=None, **kwargs):
    from .. import jit

    layer = jit.load(path_prefix)
    in_names = [s["name"] or f"x{i}" for i, s in enumerate(layer._input_specs)]
    return layer, in_names, None
