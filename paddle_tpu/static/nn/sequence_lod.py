"""Sequence ops (reference fluid/operators/sequence_ops/*, exposed via
python/paddle/static/nn/sequence_lod.py).

TPU re-design: the reference's LoD (level-of-detail) ragged tensors become
dense padded [B, T, ...] arrays + explicit per-row `length` vectors — the
same migration newer paddle made. Everything here is static-shape and
traces/compiles EXCEPT the ops whose OUTPUT SHAPE depends on the data and
which therefore need concrete lengths (eager-only): sequence_pad,
sequence_unpad, sequence_slice (out length = max requested), and
sequence_expand (row count = sum of repeats).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops._dispatch import apply, as_tensor

__all__ = [
    "sequence_conv", "sequence_softmax", "sequence_pool", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate", "sequence_reverse",
]

NEG_INF = -1e30


def _mask(T, length, B):
    """[B, T] validity mask from per-row lengths (None -> all valid)."""
    if length is None:
        return jnp.ones((B, T), bool)
    t = jnp.arange(T)[None, :]
    return t < jnp.asarray(length).reshape(-1, 1)


def sequence_softmax(input, length=None, name=None):
    """Masked softmax over the time axis (sequence_softmax_op.cc)."""
    input = as_tensor(input)

    def f(x, *rest):
        m = _mask(x.shape[1], rest[0] if rest else None, x.shape[0])
        while m.ndim < x.ndim:
            m = m[..., None]
        z = jnp.where(m, x.astype(jnp.float32), NEG_INF)
        return jax.nn.softmax(z, axis=1).astype(x.dtype) * m.astype(x.dtype)

    args = (input,) if length is None else (input, as_tensor(length))
    return apply("sequence_softmax", f, *args)


def sequence_pool(input, pool_type: str, length=None, pad_value: float = 0.0, name=None):
    """sum/average/sqrt/max/min/first/last over valid timesteps
    (sequence_pool_op.cc)."""
    input = as_tensor(input)
    pool_type = pool_type.lower()

    def f(x, *rest):
        B, T = x.shape[0], x.shape[1]
        ln = rest[0] if rest else None
        m = _mask(T, ln, B)
        while m.ndim < x.ndim:
            m = m[..., None]
        xf = x.astype(jnp.float32)
        n = jnp.maximum(m.sum(axis=1), 1)
        if pool_type == "sum":
            out = jnp.where(m, xf, 0).sum(axis=1)
        elif pool_type == "average":
            out = jnp.where(m, xf, 0).sum(axis=1) / n
        elif pool_type == "sqrt":
            out = jnp.where(m, xf, 0).sum(axis=1) / jnp.sqrt(n.astype(jnp.float32))
        elif pool_type == "max":
            out = jnp.where(m, xf, NEG_INF).max(axis=1)
        elif pool_type == "min":
            out = jnp.where(m, xf, -NEG_INF).min(axis=1)
        elif pool_type == "first":
            out = xf[:, 0]
        elif pool_type == "last":
            idx = (jnp.asarray(ln).reshape(-1) - 1 if ln is not None
                   else jnp.full((B,), T - 1))
            out = jnp.take_along_axis(
                xf, idx.reshape(-1, *([1] * (x.ndim - 1))).astype(jnp.int32), axis=1
            )[:, 0]
        else:
            raise ValueError(f"unknown pool_type {pool_type!r}")
        if ln is not None and pool_type in ("max", "min", "first", "last"):
            empty = (jnp.asarray(ln).reshape(-1, *([1] * (out.ndim - 1))) == 0)
            out = jnp.where(empty, pad_value, out)
        return out.astype(x.dtype)

    args = (input,) if length is None else (input, as_tensor(length))
    return apply(f"sequence_pool_{pool_type}", f, *args)


def sequence_first_step(input, length=None, name=None):
    return sequence_pool(input, "first", length=length)


def sequence_last_step(input, length=None, name=None):
    return sequence_pool(input, "last", length=length)


def sequence_concat(input, name=None):
    """Concatenate along time (sequence_concat_op.cc)."""
    tensors = [as_tensor(t) for t in input]
    return apply("sequence_concat", lambda *xs: jnp.concatenate(xs, axis=1), *tensors)


def sequence_slice(input, offset, length, name=None):
    """Per-row [offset, offset+length) time slice, zero-padded to max(length)
    (sequence_slice_op.cc). Static output length = max over the batch, so
    `length` must be concrete (eager-only; see module docstring)."""
    input, offset, length = as_tensor(input), as_tensor(offset), as_tensor(length)
    out_T = int(np.max(np.asarray(length._value)))

    def f(x, off, ln):
        off = off.reshape(-1, 1)
        ln = ln.reshape(-1, 1)
        t = jnp.arange(out_T)[None, :]
        idx = jnp.clip(off + t, 0, x.shape[1] - 1).astype(jnp.int32)
        shaped = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
        out = jnp.take_along_axis(x, jnp.broadcast_to(shaped, (x.shape[0], out_T) + x.shape[2:]), axis=1)
        m = (t < ln)
        while m.ndim < out.ndim:
            m = m[..., None]
        return out * m.astype(out.dtype)

    return apply("sequence_slice", f, input, offset, length)


def sequence_expand(x, y_lengths, ref_level=0, name=None):
    """Repeat row i of x y_lengths[i] times (sequence_expand_op.cc done on
    dense rows). Output row count depends on data -> eager-only with
    concrete lengths (see module docstring)."""
    x = as_tensor(x)
    reps = np.asarray(as_tensor(y_lengths)._value).astype(np.int64)
    return apply("sequence_expand", lambda v: jnp.repeat(v, jnp.asarray(reps), axis=0,
                                                         total_repeat_length=int(reps.sum())), x)


def sequence_expand_as(x, y, name=None):
    """Expand x's rows to match y's row count (sequence_expand_as_op.cc):
    each of x's N rows repeats rows(y)/N times."""
    x, y = as_tensor(x), as_tensor(y)
    n, m = x.shape[0], y.shape[0]
    if m % n:
        raise ValueError(f"cannot expand {n} rows as {m} rows")
    return apply("sequence_expand_as", lambda v: jnp.repeat(v, m // n, axis=0), x)


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Packed [sum_T, D] + lengths -> (padded [B, maxT, D], lengths)
    (sequence_pad_op.cc). Eager: output shape depends on lengths."""
    x = as_tensor(x)
    if length is None:
        raise ValueError("sequence_pad needs per-sequence `length`")
    lens = np.asarray(as_tensor(length)._value).astype(np.int64)
    T = int(maxlen) if maxlen else int(lens.max())
    pv = float(np.asarray(as_tensor(pad_value)._value).reshape(-1)[0])
    starts = np.concatenate([[0], np.cumsum(lens)[:-1]])

    def f(v):
        rows = []
        for s, ln in zip(starts, lens):
            seg = v[int(s): int(s + min(ln, T))]
            pad = [(0, T - seg.shape[0])] + [(0, 0)] * (v.ndim - 1)
            rows.append(jnp.pad(seg, pad, constant_values=pv))
        return jnp.stack(rows)

    return apply("sequence_pad", f, x), Tensor(jnp.asarray(lens))


def sequence_unpad(x, length, name=None):
    """Inverse of sequence_pad: [B, T, D] + lengths -> packed [sum_T, D]
    (sequence_unpad_op.cc). Eager: output rows depend on lengths."""
    x = as_tensor(x)
    lens = np.asarray(as_tensor(length)._value).astype(np.int64)

    def f(v):
        return jnp.concatenate([v[i, : int(ln)] for i, ln in enumerate(lens)], axis=0)

    return apply("sequence_unpad", f, x)


def sequence_reshape(input, new_dim: int, name=None):
    """Re-chunk the trailing dim (sequence_reshape_op.cc): [N, D] ->
    [N*D/new_dim, new_dim]."""
    input = as_tensor(input)
    return apply("sequence_reshape", lambda v: v.reshape(-1, new_dim), input)


def sequence_scatter(input, index, updates, name=None):
    """x[index[i]] += updates[i] (sequence_scatter_op.cc)."""
    input, index, updates = as_tensor(input), as_tensor(index), as_tensor(updates)
    return apply("sequence_scatter",
                 lambda x, i, u: x.at[i.astype(jnp.int32)].add(u.astype(x.dtype)),
                 input, index, updates)


def sequence_enumerate(input, win_size: int, pad_value: int = 0, name=None):
    """Sliding windows of ids (sequence_enumerate_op.cc): [B, T] ->
    [B, T, win_size], windows past the end fill pad_value."""
    input = as_tensor(input)

    def f(x):
        T = x.shape[-1]
        t = jnp.arange(T)[:, None] + jnp.arange(win_size)[None, :]
        valid = t < T
        idx = jnp.clip(t, 0, T - 1)
        win = jnp.take(x, idx, axis=-1)
        return jnp.where(valid, win, pad_value)

    return apply("sequence_enumerate", f, input)


def sequence_reverse(x, length=None, name=None):
    """Reverse each row's valid prefix, padding stays in place
    (sequence_reverse_op.cc)."""
    x = as_tensor(x)

    def f(v, *rest):
        B, T = v.shape[0], v.shape[1]
        ln = (rest[0].reshape(-1, 1).astype(jnp.int32) if rest
              else jnp.full((B, 1), T, jnp.int32))
        t = jnp.arange(T)[None, :]
        idx = jnp.where(t < ln, ln - 1 - t, t).astype(jnp.int32)
        shaped = idx.reshape(idx.shape + (1,) * (v.ndim - 2))
        return jnp.take_along_axis(v, jnp.broadcast_to(shaped, (B, T) + v.shape[2:]), axis=1)

    args = (x,) if length is None else (x, as_tensor(length))
    return apply("sequence_reverse", f, *args)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, length=None, name=None):
    """Context-window projection (sequence_conv_op.cc): each timestep's
    output = flatten(window of filter_size steps) @ W. Dense re-design via
    gather + one matmul (im2col rides the MXU)."""
    from ... import nn

    input = as_tensor(input)
    D = input.shape[-1]
    lin = nn.Linear(filter_size * D, num_filters,
                    bias_attr=bias_attr if bias_attr is not None else True)
    start = padding_start if padding_start is not None else -((filter_size - 1) // 2)

    def f(x):
        B, T = x.shape[0], x.shape[1]
        t = jnp.arange(T)[:, None] + jnp.arange(filter_size)[None, :] + start
        valid = (t >= 0) & (t < T)
        idx = jnp.clip(t, 0, T - 1)
        win = x[:, idx]  # [B, T, filter_size, D]
        win = win * valid[None, :, :, None].astype(x.dtype)
        return win.reshape(B, T, filter_size * D)

    windows = apply("sequence_conv_im2col", f, input)
    out = lin(windows)
    if act:
        out = getattr(nn.functional, act)(out)
    return out
