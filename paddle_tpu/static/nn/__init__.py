"""paddle.static.nn — static-graph layer builders
(python/paddle/static/nn/__init__.py, 39 names).

TPU re-design: the reference's builders append ops + create scoped
parameters in the default Program via LayerHelper. Here parameters have
eager identity (core Tensors) and static capture happens at the dispatch
seam, so each builder simply instantiates the matching nn.Layer (fresh
params per call, like LayerHelper's unique names) and applies it; control
flow delegates to the dy2static convert calls (lax.cond/while_loop under a
trace, plain python eagerly); the sequence_* family lives in
sequence_lod.py on dense padded tensors + lengths instead of LoD."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops._dispatch import apply, as_tensor
from .sequence_lod import (  # noqa: F401
    sequence_concat,
    sequence_conv,
    sequence_enumerate,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_pad,
    sequence_pool,
    sequence_reshape,
    sequence_reverse,
    sequence_scatter,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
)

__all__ = [
    'fc', 'batch_norm', 'bilinear_tensor_product', 'embedding', 'case',
    'cond', 'conv2d', 'conv2d_transpose', 'conv3d', 'conv3d_transpose',
    'data_norm', 'deform_conv2d', 'group_norm', 'instance_norm',
    'layer_norm', 'nce', 'prelu', 'py_func', 'row_conv', 'spectral_norm',
    'switch_case', 'while_loop', 'sparse_embedding', 'sequence_conv',
    'sequence_softmax', 'sequence_pool', 'sequence_concat',
    'sequence_first_step', 'sequence_last_step', 'sequence_slice',
    'sequence_expand', 'sequence_expand_as', 'sequence_pad',
    'sequence_unpad', 'sequence_reshape', 'sequence_scatter',
    'sequence_enumerate', 'sequence_reverse', 'StaticRNN',
]


def _act(out, act):
    if act:
        from ... import nn

        return getattr(nn.functional, act)(out)
    return out


# ---------------- parameterized builders ----------------
def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None, activation=None, name=None):
    """Fully connected (reference static/nn/common.py fc): flattens trailing
    dims past num_flatten_dims, fresh weights per call."""
    from ... import nn

    xs = [as_tensor(v) for v in (x if isinstance(x, (list, tuple)) else [x])]
    outs = None
    for v in xs:
        in_f = int(np.prod(v.shape[num_flatten_dims:]))
        lin = nn.Linear(in_f, size, weight_attr=weight_attr, bias_attr=bias_attr)
        flat = v.reshape(list(v.shape[:num_flatten_dims]) + [in_f])
        o = lin(flat)
        outs = o if outs is None else outs + o
    return _act(outs, activation)


def embedding(input, size, is_sparse=False, is_distributed=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    from ... import nn

    emb = nn.Embedding(size[0], size[1], padding_idx=padding_idx, weight_attr=param_attr)
    return emb(as_tensor(input))


def sparse_embedding(input, size, padding_idx=None, is_test=False, entry=None,
                     table_class="MemorySparseTable", param_attr=None, dtype="float32", name=None):
    """PS-backed embedding in the reference (the_one_ps); dense table here —
    the distributed/ps package provides the server-side analog."""
    return embedding(input, size, padding_idx=padding_idx, param_attr=param_attr, dtype=dtype)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True, act=None,
           name=None, data_format="NCHW"):
    from ... import nn

    conv = nn.Conv2D(input.shape[1] if data_format == "NCHW" else input.shape[-1],
                     num_filters, filter_size, stride=stride, padding=padding,
                     dilation=dilation, groups=groups, weight_attr=param_attr,
                     bias_attr=bias_attr, data_format=data_format)
    return _act(conv(as_tensor(input)), act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True, act=None,
           name=None, data_format="NCDHW"):
    from ... import nn

    conv = nn.Conv3D(input.shape[1], num_filters, filter_size, stride=stride,
                     padding=padding, dilation=dilation, groups=groups,
                     weight_attr=param_attr, bias_attr=bias_attr)
    return _act(conv(as_tensor(input)), act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1, param_attr=None,
                     bias_attr=None, use_cudnn=True, act=None, name=None,
                     data_format="NCHW"):
    from ... import nn

    conv = nn.Conv2DTranspose(input.shape[1], num_filters, filter_size,
                              stride=stride, padding=padding, dilation=dilation,
                              groups=groups, weight_attr=param_attr, bias_attr=bias_attr)
    out = conv(as_tensor(input), output_size=output_size) if output_size is not None else conv(as_tensor(input))
    return _act(out, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1, param_attr=None,
                     bias_attr=None, use_cudnn=True, act=None, name=None,
                     data_format="NCDHW"):
    from ... import nn

    conv = nn.Conv3DTranspose(input.shape[1], num_filters, filter_size,
                              stride=stride, padding=padding, dilation=dilation,
                              groups=groups, weight_attr=param_attr, bias_attr=bias_attr)
    return _act(conv(as_tensor(input)), act)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW", name=None,
               moving_mean_name=None, moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from ... import nn

    C = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    bn = nn.BatchNorm(C, momentum=momentum, epsilon=epsilon, weight_attr=param_attr,
                      bias_attr=bias_attr, data_format=data_layout,
                      use_global_stats=use_global_stats)
    if is_test:
        bn.eval()
    return _act(bn(as_tensor(input)), act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    from ... import nn

    shape = list(input.shape[begin_norm_axis:])
    ln = nn.LayerNorm(shape, epsilon=epsilon,
                      weight_attr=param_attr if scale else False,
                      bias_attr=bias_attr if shift else False)
    return _act(ln(as_tensor(input)), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    from ... import nn

    inorm = nn.InstanceNorm2D(input.shape[1], epsilon=epsilon,
                              weight_attr=param_attr, bias_attr=bias_attr)
    return inorm(as_tensor(input))


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from ... import nn

    gn = nn.GroupNorm(groups, input.shape[1], epsilon=epsilon,
                      weight_attr=param_attr, bias_attr=bias_attr)
    return _act(gn(as_tensor(input)), act)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, data_layout="NCHW",
              in_place=False, name=None, moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1, sync_stats=False,
              summary_decay_rate=0.9999999, enable_scale_and_shift=False):
    """Summary-statistics normalization (data_norm_op.cc): learned batch
    (size, sum, square_sum) accumulators normalize without batch coupling."""
    from ... import nn

    input = as_tensor(input)
    C = input.shape[-1]
    layer = nn.Layer()
    bsize = layer.create_parameter([C], default_initializer=nn.initializer.Constant(1e4))
    bsum = layer.create_parameter([C], default_initializer=nn.initializer.Constant(0.0))
    bsq = layer.create_parameter([C], default_initializer=nn.initializer.Constant(1e4))

    def f(x, n, s, sq):
        mean = s / n
        scale = jnp.sqrt(n / jnp.maximum(sq - s * s / n, epsilon))
        return (x - mean) * scale

    return _act(apply("data_norm", f, input, bsize, bsum, bsq), act)


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None, bias_attr=None):
    from ... import nn

    bl = nn.Bilinear(x.shape[-1], y.shape[-1], size, weight_attr=param_attr, bias_attr=bias_attr)
    return _act(bl(as_tensor(x), as_tensor(y)), act)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    """Channel/element/all-shared learned negative slope (prelu op)."""
    from ... import nn

    x = as_tensor(x)
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [x.shape[1] if data_format == "NCHW" else x.shape[-1]]
    elif mode == "element":
        shape = list(x.shape[1:])
    else:
        raise ValueError(f"mode must be all|channel|element, got {mode!r}")
    layer = nn.Layer()
    alpha = layer.create_parameter(shape, default_initializer=nn.initializer.Constant(0.25))

    def f(v, a):
        if mode == "channel" and data_format == "NCHW":
            a = a.reshape((1, -1) + (1,) * (v.ndim - 2))
        return jnp.where(v >= 0, v, a * v)

    return apply("prelu", f, x, alpha)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (row_conv_op.cc): out[t] = sum_{i<=k}
    w[i] * x[t+i] over a [k+1, D] filter."""
    from ... import nn

    input = as_tensor(input)
    D = input.shape[-1]
    k = future_context_size
    layer = nn.Layer()
    w = layer.create_parameter([k + 1, D])

    def f(x, wv):
        T = x.shape[1]
        t = jnp.arange(T)[:, None] + jnp.arange(k + 1)[None, :]
        valid = t < T
        idx = jnp.clip(t, 0, T - 1)
        win = x[:, idx]  # [B, T, k+1, D]
        win = win * valid[None, :, :, None].astype(x.dtype)
        return jnp.einsum("btkd,kd->btd", win.astype(jnp.float32), wv.astype(jnp.float32)).astype(x.dtype)

    return _act(apply("row_conv", f, input, w), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ... import nn

    sn = nn.SpectralNorm(weight.shape, dim=dim, power_iters=power_iters, epsilon=eps)
    return sn(as_tensor(weight))


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (nce_op.cc): logistic loss on the
    true class + uniformly sampled noise classes."""
    from ... import nn
    from ...core import random as _random

    input, label = as_tensor(input), as_tensor(label)
    D = input.shape[-1]
    k = num_neg_samples or 5
    layer = nn.Layer()
    w = layer.create_parameter([num_total_classes, D])
    b = layer.create_parameter([num_total_classes], is_bias=True)

    def f(x, y, wv, bv):
        B = x.shape[0]
        key = _random.next_key()
        noise = jax.random.randint(key, (B, k), 0, num_total_classes)
        yv = y.reshape(-1).astype(jnp.int32)
        pos = jnp.einsum("bd,bd->b", x, wv[yv]) + bv[yv]
        neg = jnp.einsum("bd,bkd->bk", x, wv[noise]) + bv[noise]
        loss = jax.nn.softplus(-pos) + jax.nn.softplus(neg).sum(-1)
        return loss.reshape(-1, 1)

    return apply("nce", f, input, label, w, b)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    from ... import nn
    from ...vision.ops import deform_conv2d as _dc

    C = input.shape[1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) else (filter_size, filter_size)
    layer = nn.Layer()
    weight = layer.create_parameter([num_filters, C // groups, ks[0], ks[1]])
    bias = layer.create_parameter([num_filters], is_bias=True) if bias_attr is not False else None
    return _dc(as_tensor(input), as_tensor(offset), weight, bias=bias, stride=stride,
               padding=padding, dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=as_tensor(mask) if mask is not None else None)


# ---------------- control flow ----------------
def _capture_subprogram(run_fn):
    """Record run_fn's ops into a fresh sub-Program (the reference's
    sub-block, while_op/conditional_block design). Returns
    (subprog, result)."""
    from ..program import Program, _program_stack

    sub = Program()
    _program_stack.append(sub)
    try:
        result = run_fn()
    finally:
        _program_stack.pop()
    return sub, result


def _sub_externals(subs, internal_tids):
    """Tensors a sub-program reads that it neither produces nor receives as
    carries: they become inputs of the parent control-flow node, so feeds
    propagate into the block at replay."""
    from ..program import _OpNode

    produced = set(internal_tids)
    ext_ids, ext_tensors = [], []
    for sub in subs:
        for node in sub.nodes:
            if not isinstance(node, _OpNode):
                continue
            for tid in node.in_ids:
                if tid not in produced and tid not in ext_ids:
                    ext_ids.append(tid)
                    ext_tensors.append(sub.tensors[tid])
            produced.update(node.out_ids)
    return ext_ids, ext_tensors


def _sub_produced(sub):
    from ..program import _OpNode

    out = set()
    for node in sub.nodes:
        if isinstance(node, _OpNode):
            out.update(node.out_ids)
    return out


def _add_passthrough_externals(tensors, produced, skip, ext_ids, ext_tensors):
    """Block RESULTS that no recorded op produced (identity branches like
    `lambda: x` over a placeholder) must still be node inputs, else replay
    falls back to their capture-time values and feeds never reach them."""
    for t in tensors:
        if not isinstance(t, Tensor):
            continue
        tid = id(t)
        if tid not in produced and tid not in skip and tid not in ext_ids:
            ext_ids.append(tid)
            ext_tensors.append(t)


def _sub_replay(sub, env):
    from ..program import _OpNode

    for node in sub.nodes:
        if not isinstance(node, _OpNode):
            continue
        vals = node.fn(*[env.get(tid, None) if env.get(tid) is not None
                         else sub.tensors[tid]._value for tid in node.in_ids])
        import jax

        for tid, leaf in zip(node.out_ids, jax.tree_util.tree_leaves(vals)):
            env[tid] = leaf


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Two-branch conditional (reference control_flow.py cond): traced pred
    runs both branches and selects leaf-wise; concrete pred runs one.

    In static-capture mode each branch records into a sub-Program
    (conditional_block_op design) and ONE node replays them with the feeds
    flowing in — the conditional survives into the captured program."""
    import jax.numpy as jnp

    from ...jit.dy2static import convert_ifelse
    from ...nn.layer.layers import in_dynamic_mode

    t_fn = true_fn if true_fn is not None else (lambda: None)
    f_fn = false_fn if false_fn is not None else (lambda: None)

    def norm(fn):
        def run(_vars):
            out = fn()
            leaves = out if isinstance(out, (list, tuple)) else (out,)
            return tuple(leaves)
        return run

    def unwrap(res):
        if len(res) == 1:
            return res[0]
        return list(res)

    if in_dynamic_mode():
        return unwrap(convert_ifelse(pred, norm(t_fn), norm(f_fn), (), names=()))

    pred_t = as_tensor(pred)
    sub_t, outs_t = _capture_subprogram(lambda: norm(t_fn)(()))
    sub_f, outs_f = _capture_subprogram(lambda: norm(f_fn)(()))
    if len(outs_t) != len(outs_f):
        raise ValueError("cond branches must return the same number of outputs")
    t_out_ids = [id(o) for o in outs_t]
    f_out_ids = [id(o) for o in outs_f]
    ext_ids, ext_tensors = _sub_externals([sub_t, sub_f], [])
    _add_passthrough_externals(outs_t, _sub_produced(sub_t), set(), ext_ids, ext_tensors)
    _add_passthrough_externals(outs_f, _sub_produced(sub_f), set(), ext_ids, ext_tensors)

    def fn(p_raw, *ext_raws):
        ext_env = dict(zip(ext_ids, ext_raws))
        env_t = dict(ext_env)
        env_f = dict(ext_env)
        _sub_replay(sub_t, env_t)
        _sub_replay(sub_f, env_f)
        c = jnp.squeeze(jnp.asarray(p_raw)).astype(bool)
        outs = []
        for ti, fi, ot, of in zip(t_out_ids, f_out_ids, outs_t, outs_f):
            tv = env_t.get(ti, ot._value if isinstance(ot, Tensor) else ot)
            fv = env_f.get(fi, of._value if isinstance(of, Tensor) else of)
            outs.append(jnp.where(c, tv, fv))
        return tuple(outs)

    res = apply("cond", fn, pred_t, *ext_tensors)
    if not isinstance(res, (tuple, list)):
        return res
    return unwrap(tuple(res))


def case(pred_fn_pairs, default=None, name=None):
    """Chained conditionals (reference case): first true pred wins."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if not rest:
        return cond(pred, fn, default if default is not None else fn)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Indexed dispatch (reference switch_case): lax.switch when traced."""
    from ...jit.dy2static import _is_traced, _raw

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    idx = as_tensor(branch_index) if not isinstance(branch_index, (int, np.integer)) else branch_index
    raw = _raw(idx) if isinstance(idx, Tensor) else idx
    if isinstance(raw, jax.core.Tracer):
        if default is not None:
            fns = fns + [default]
        # map branch_index -> dense position (keys may be sparse)
        positions = jnp.full((max(keys) + 2,), len(fns) - 1, jnp.int32)
        for pos, kk in enumerate(keys):
            positions = positions.at[kk].set(pos)
        sel = positions[jnp.clip(jnp.asarray(raw).astype(jnp.int32), 0, max(keys) + 1)]
        outs = [f() for f in fns]
        flats = []
        treedef0 = None
        for o in outs:
            leaves, treedef = jax.tree_util.tree_flatten(
                o, is_leaf=lambda v: isinstance(v, Tensor))
            if treedef0 is None:
                treedef0 = treedef
            elif treedef != treedef0:
                raise ValueError(
                    "switch_case branches must return the same structure "
                    f"under a trace; got {treedef0} vs {treedef}")
            flats.append([v._value if isinstance(v, Tensor) else jnp.asarray(v)
                          for v in leaves])
        picked = [Tensor(jnp.stack(per_leaf)[sel])
                  for per_leaf in zip(*flats)]
        return jax.tree_util.tree_unflatten(treedef0, picked)
    key = int(raw)
    for kk, f in items:
        if kk == key:
            return f()
    if default is not None:
        return default()
    return fns[-1]()


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """reference while_loop: compiled lax.while_loop under a trace, python
    loop eagerly (jit/dy2static convert_while).

    In static-capture mode the loop records as ONE Program node (the
    reference's while_op role): replay re-runs the convert call on the
    replay values, so the trip count follows the FEEDS — the loop is not
    unrolled at capture time."""
    from ...jit.dy2static import convert_while
    from ...nn.layer.layers import in_dynamic_mode

    n = len(loop_vars)
    names = tuple(f"var{i}" for i in range(n))

    def body_wrap(vars_):
        out = body(*vars_)
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    if in_dynamic_mode():
        out = convert_while(lambda vars_: cond(*vars_), body_wrap,
                            tuple(loop_vars), names=names)
        return list(out)

    # static capture (while_op sub-block design): record cond and body ONCE
    # into sub-Programs against carry placeholders; the parent node replays
    # them inside lax.while_loop, with external reads (feeds, upstream
    # results) wired in as node inputs
    import jax.numpy as jnp
    from jax import lax

    vars_t = [as_tensor(v) for v in loop_vars]
    carries = [Tensor(v._value) for v in vars_t]  # placeholders for the block
    carry_ids = [id(c) for c in carries]
    sub_c, cond_out = _capture_subprogram(lambda: as_tensor(cond(*carries)))
    sub_b, body_out = _capture_subprogram(lambda: body_wrap(tuple(carries)))
    body_out = [as_tensor(o) for o in body_out]
    if len(body_out) != n:
        raise ValueError(f"while_loop body returned {len(body_out)} values for {n} loop_vars")
    cond_id = id(cond_out)
    out_ids = [id(o) for o in body_out]
    ext_ids, ext_tensors = _sub_externals([sub_c, sub_b], carry_ids)
    carry_set = set(carry_ids)
    _add_passthrough_externals(body_out + [cond_out], _sub_produced(sub_b) | _sub_produced(sub_c),
                               carry_set, ext_ids, ext_tensors)

    def fn(*raws):
        carry0 = tuple(jnp.asarray(r) for r in raws[:n])
        ext_env = dict(zip(ext_ids, raws[n:]))

        def cond_fn(carry):
            env = dict(ext_env)
            env.update(zip(carry_ids, carry))
            _sub_replay(sub_c, env)
            c = env.get(cond_id, cond_out._value)
            return jnp.squeeze(jnp.asarray(c)).astype(bool)

        def body_fn(carry):
            env = dict(ext_env)
            env.update(zip(carry_ids, carry))
            _sub_replay(sub_b, env)
            return tuple(
                jnp.asarray(env.get(oid, o._value)).astype(c0.dtype).reshape(c0.shape)
                for oid, o, c0 in zip(out_ids, body_out, carry0))

        return lax.while_loop(cond_fn, body_fn, carry0)

    outs = apply("while_loop", fn, *(vars_t + ext_tensors))
    if not isinstance(outs, (tuple, list)):
        return [outs]
    return list(outs)[:n]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None, name=None):
    """Host-python op (py_func_op.cc): eager calls func directly; under a
    trace it becomes jax.pure_callback with `out` as the shape template."""
    xs = [as_tensor(v) for v in (x if isinstance(x, (list, tuple)) else [x])]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), o._jdtype()) for o in outs]

    def f(*vals):
        def host(*np_vals):
            res = func(*np_vals)
            res = res if isinstance(res, (list, tuple)) else [res]
            return tuple(np.asarray(r, dtype=s.dtype) for r, s in zip(res, shapes))

        res = jax.pure_callback(host, tuple(shapes), *vals)
        return res if len(res) > 1 else res[0]

    return apply("py_func", f, *xs)


class StaticRNN:
    """Step-wise RNN builder (reference control_flow.py StaticRNN).

    TPU re-design over the Program capture: the `with rnn.step():` block runs
    once in static mode, recording its ops into the default Program;
    step_input/memory hand out concrete per-step tensors (t=0 slice / init)
    so the block executes normally; `rnn()` then replays exactly the
    recorded node range per timestep with that step's slices and memories
    fed in, stacking outputs on the time axis. Sequences are dense
    [B, T, ...] (the LoD-free contract used across static.nn)."""

    def __init__(self, name=None):
        self._seq_inputs = []   # (placeholder Tensor, source Tensor)
        self._memories = []     # (placeholder Tensor, init value)
        self._mem_updates = {}  # id(placeholder) -> updated Tensor
        self._step_outputs = []
        self._range = None

    def step(self):
        from ...nn.layer import layers as _layers
        from ..program import default_main_program

        if _layers.in_dynamic_mode():
            raise RuntimeError("StaticRNN requires paddle.enable_static()")
        rnn = self
        prog = default_main_program()

        class _Guard:
            def __enter__(self):
                self._start = len(prog.nodes)
                return rnn

            def __exit__(self, *exc):
                rnn._range = (self._start, len(prog.nodes))
                return False

        return _Guard()

    def step_input(self, x):
        x = as_tensor(x)
        ph = Tensor(x._value[:, 0])
        self._seq_inputs.append((ph, x))
        return ph

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape, batch_ref)")
            B = batch_ref.shape[init_batch_dim_idx]
            dims = tuple(d for d in shape if d not in (-1, None))
            init = Tensor(jnp.full((B,) + dims, init_value, jnp.float32))
        init = as_tensor(init)
        ph = Tensor(init._value)
        self._memories.append((ph, init._value))
        return ph

    def update_memory(self, mem, new_val):
        self._mem_updates[id(mem)] = as_tensor(new_val)

    def step_output(self, o):
        self._step_outputs.append(as_tensor(o))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        from ..program import _OpNode, default_main_program

        if self._range is None:
            raise RuntimeError("run the step block (`with rnn.step():`) first")
        if not self._seq_inputs:
            raise RuntimeError("StaticRNN needs at least one step_input")
        prog = default_main_program()
        nodes = [n for n in prog.nodes[self._range[0]: self._range[1]]
                 if isinstance(n, _OpNode)]
        T = self._seq_inputs[0][1].shape[1]
        mems = {id(ph): val for ph, val in self._memories}
        outs = []
        for t in range(T):
            env = {id(ph): src._value[:, t] for ph, src in self._seq_inputs}
            env.update(mems)
            for node in nodes:
                vals = node.fn(*[env.get(tid, None) if env.get(tid) is not None
                                 else prog.tensors[tid]._value for tid in node.in_ids])
                for tid, leaf in zip(node.out_ids, jax.tree_util.tree_leaves(vals)):
                    env[tid] = leaf
            mems = {pid: env.get(id(new), new._value)
                    for pid, new in ((id(ph), self._mem_updates.get(id(ph)))
                                     for ph, _ in self._memories) if new is not None}
            for ph, init in self._memories:
                mems.setdefault(id(ph), env[id(ph)])
            outs.append([env.get(id(o), o._value) for o in self._step_outputs])
        stacked = [Tensor(jnp.stack([step[i] for step in outs], axis=1))
                   for i in range(len(self._step_outputs))]
        return stacked[0] if len(stacked) == 1 else stacked
