"""Static graph: Program capture + Executor replay, TPU-style.

Reference surface: paddle.static (Program/Executor/program_guard/data/
append_backward — SURVEY §2.5, §3.3). Architecture here: the single op
dispatch seam (ops/_dispatch.apply) appends every executed op to the active
Program as a replayable node (pure_fn + input/output tensor identities) while
still computing placeholder values eagerly for shape/dtype propagation.
Executor.run substitutes feeds and replays the node list as one pure function
— jit-compiled by XLA per feed signature, which IS the reference's
"Program -> compiled executor" pipeline (interpretercore.cc's job done by
XLA; SURVEY §3.3 TPU note).

Gradients: append_backward records a GradNode that differentiates the replay
function with jax.grad — the static analog of the reference's
append_backward program rewriting (python/paddle/fluid/backward.py:1865).
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor


class _OpNode:
    __slots__ = ("op_name", "fn", "in_ids", "out_ids")

    def __init__(self, op_name, fn, in_ids, out_ids):
        self.op_name, self.fn = op_name, fn
        self.in_ids, self.out_ids = in_ids, out_ids


class _GradNode:
    """Computes d(loss)/d(wrt) by differentiating the forward replay."""

    __slots__ = ("loss_id", "wrt_ids", "grad_ids", "fwd_len")

    def __init__(self, loss_id, wrt_ids, grad_ids, fwd_len):
        self.loss_id, self.wrt_ids, self.grad_ids = loss_id, wrt_ids, grad_ids
        self.fwd_len = fwd_len  # only nodes before this index feed the loss


class _JvpNode:
    """Forward-mode grads: jvp of the forward replay (reference
    primapi.forward_grad's linearize-program rewrite, fluid/prim/)."""

    __slots__ = ("out_ids", "in_ids", "tangent_ids", "jvp_ids", "fwd_len")

    def __init__(self, out_ids, in_ids, tangent_ids, jvp_ids, fwd_len):
        self.out_ids, self.in_ids = out_ids, in_ids
        self.tangent_ids, self.jvp_ids = tangent_ids, jvp_ids
        self.fwd_len = fwd_len


class _UpdateNode:
    """Optimizer update: consumes grads, writes new param values (side effect)."""

    __slots__ = ("param_ids", "grad_ids", "optimizer", "opt_state", "params_ref")

    def __init__(self, param_ids, grad_ids, optimizer, params_ref):
        self.param_ids, self.grad_ids = param_ids, grad_ids
        self.optimizer = optimizer
        self.opt_state = None
        self.params_ref = params_ref  # {tid: Parameter}


class Program:
    def __init__(self):
        self.nodes: List[object] = []
        self.placeholders: Dict[str, Tensor] = {}  # name -> placeholder Tensor
        self.tensors: Dict[int, Tensor] = {}       # tid -> Tensor (live objects)
        self.random_seed = 0
        self._fetch_cache = {}

    # ---- reference Program surface ----
    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.nodes = list(self.nodes)
        p.placeholders = dict(self.placeholders)
        p.tensors = dict(self.tensors)
        p.random_seed = self.random_seed
        return p

    def global_block(self):
        return self

    # block-like surface
    @property
    def ops(self):
        return self.nodes

    def var(self, name):
        if name in self.placeholders:
            return self.placeholders[name]
        for t in self.tensors.values():
            if getattr(t, "name", None) == name:
                return t
        raise KeyError(name)

    def all_parameters(self):
        return [t for t in self.tensors.values() if isinstance(t, Parameter)]

    def list_vars(self):
        return list(self.placeholders.values()) + list(self.tensors.values())

    def state_dict(self, mode="all"):
        return {getattr(p, "name", f"param_{i}"): p for i, p in enumerate(self.all_parameters())}

    def set_state_dict(self, state):
        by_name = {getattr(p, "name", None): p for p in self.all_parameters()}
        for k, v in state.items():
            if k in by_name:
                by_name[k]._set_value_raw(jnp.asarray(v.numpy() if hasattr(v, "numpy") else v))

    def _register(self, t: Tensor):
        self.tensors[id(t)] = t

    def _record(self, op_name, fn, in_tensors, out_tensors):
        for t in list(in_tensors) + list(out_tensors):
            self._register(t)
        self.nodes.append(_OpNode(op_name, fn, [id(t) for t in in_tensors], [id(t) for t in out_tensors]))
        self._fetch_cache.clear()


_default_main = Program()
_default_startup = Program()
_program_stack: List[Program] = []


def default_main_program() -> Program:
    return _program_stack[-1] if _program_stack else _default_main


def default_startup_program() -> Program:
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Program = None):
    global _default_startup
    _program_stack.append(main_program)
    old_startup = _default_startup
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _program_stack.pop()
        _default_startup = old_startup


def capture_active() -> bool:
    from ..nn.layer.layers import in_dynamic_mode

    return not in_dynamic_mode()


def record_op(op_name, fn, in_tensors, out_tensors):
    default_main_program()._record(op_name, fn, in_tensors, out_tensors)


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Placeholder variable (reference static.data). None/-1 dims capture with
    extent 1; the replay function is shape-polymorphic so feeds of any batch
    size work."""
    from ..core.dtype import to_jax_dtype

    concrete = tuple(1 if (d is None or (isinstance(d, int) and d < 0)) else int(d) for d in shape)
    t = Tensor(jnp.zeros(concrete, to_jax_dtype(dtype)))
    t.name = name
    t._is_placeholder = True
    prog = default_main_program()
    prog.placeholders[name] = t
    prog._register(t)
    return t


# ---- replay ----
def _replay(prog: Program, env: Dict[int, jnp.ndarray], upto: Optional[int] = None):
    """Walk nodes, computing outputs into env. Values default to captured."""

    def val(tid):
        if tid in env:
            return env[tid]
        return prog.tensors[tid]._value

    for node in prog.nodes[: upto if upto is not None else len(prog.nodes)]:
        if isinstance(node, _OpNode):
            outs = node.fn(*[val(t) for t in node.in_ids])
            leaves = jax.tree_util.tree_leaves(outs)
            for tid, leaf in zip(node.out_ids, leaves):
                env[tid] = leaf
        elif isinstance(node, _GradNode):
            grads = _compute_grads(prog, env, node)
            for tid, g in zip(node.grad_ids, grads):
                env[tid] = g
        elif isinstance(node, _JvpNode):
            jvps = _compute_jvps(prog, env, node)
            for tid, g in zip(node.jvp_ids, jvps):
                env[tid] = g
        elif isinstance(node, _UpdateNode):
            _apply_update(prog, env, node)
    return env


def _forward_fn(prog: Program, node: _GradNode, feeds: Dict[int, jnp.ndarray]):
    def f(wrt_vals):
        env = dict(feeds)
        env.update(dict(zip(node.wrt_ids, wrt_vals)))
        _replay_pure(prog, env, node.fwd_len)
        return env[node.loss_id].astype(jnp.float32).sum()

    return f


def _replay_pure(prog, env, upto):
    """Differentiable replay: like _replay but without _UpdateNode side
    effects. _GradNode/_JvpNode values ARE replayed (jax.grad/jvp of the
    inner replay is itself differentiable) so forward-over-reverse —
    forward_grad of static.gradients outputs, the canonical HVP — sees the
    real gradient path instead of a zero constant."""
    for n in prog.nodes[:upto]:
        if isinstance(n, _OpNode):
            outs = n.fn(*[env.get(t, None) if env.get(t) is not None else prog.tensors[t]._value for t in n.in_ids])
            for tid, leaf in zip(n.out_ids, jax.tree_util.tree_leaves(outs)):
                env[tid] = leaf
        elif isinstance(n, _GradNode):
            for tid, g in zip(n.grad_ids, _compute_grads(prog, env, n)):
                env[tid] = g
        elif isinstance(n, _JvpNode):
            for tid, g in zip(n.jvp_ids, _compute_jvps(prog, env, n)):
                env[tid] = g


def _compute_grads(prog, env, node: _GradNode):
    feeds = {tid: v for tid, v in env.items()}
    wrt_vals = [env.get(t, prog.tensors[t]._value) for t in node.wrt_ids]
    for t in node.wrt_ids:
        feeds.pop(t, None)
    return jax.grad(_forward_fn(prog, node, feeds))(wrt_vals)


def _compute_jvps(prog, env, node: _JvpNode):
    feeds = {tid: v for tid, v in env.items()}
    in_vals = [env.get(t, prog.tensors[t]._value) for t in node.in_ids]
    tangents = [env.get(t, prog.tensors[t]._value) if t is not None
                else jnp.ones_like(v)
                for t, v in zip(node.tangent_ids, in_vals)]
    for t in node.in_ids:
        feeds.pop(t, None)

    def f(*vals):
        env2 = dict(feeds)
        env2.update(dict(zip(node.in_ids, vals)))
        _replay_pure(prog, env2, node.fwd_len)
        return [env2[o] for o in node.out_ids]

    _, jvps = jax.jvp(f, tuple(in_vals),
                      tuple(t.astype(v.dtype) for t, v in zip(tangents, in_vals)))
    return jvps


def _apply_update(prog, env, node: _UpdateNode):
    params = {str(t): env.get(t, prog.tensors[t]._value) for t in node.param_ids}
    grads = {str(t): env[g] for t, g in zip(node.param_ids, node.grad_ids)}
    opt = node.optimizer
    if node.opt_state is None:
        node.opt_state = opt.init_state_pytree(params)
    new_params, node.opt_state = opt.apply_gradients(params, grads, node.opt_state, lr=opt.get_lr())
    for t in node.param_ids:
        env[t] = new_params[str(t)]
        node.params_ref[t]._set_value_raw(new_params[str(t)])


# ---- autodiff API ----
def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    """Record gradient computation for every trainable Parameter feeding loss
    (reference: fluid/backward.py:1865). Returns [(param, grad_var)]."""
    prog = default_main_program()
    params = parameter_list or [p for p in prog.all_parameters() if not p.stop_gradient]
    params = [p for p in params if no_grad_set is None or p not in no_grad_set]
    grad_vars = []
    for p in params:
        g = Tensor(jnp.zeros_like(p._value))
        g.name = f"{getattr(p, 'name', 'param')}@GRAD"
        prog._register(g)
        grad_vars.append(g)
    node = _GradNode(id(loss), [id(p) for p in params], [id(g) for g in grad_vars], len(prog.nodes))
    prog.nodes.append(node)
    prog._fetch_cache.clear()
    return list(zip(params, grad_vars))


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Grad vars of targets wrt inputs (reference static.gradients)."""
    prog = default_main_program()
    tgt = targets[0] if isinstance(targets, (list, tuple)) else targets
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    grad_vars = []
    for p in inputs:
        g = Tensor(jnp.zeros_like(p._value))
        prog._register(g)
        grad_vars.append(g)
    prog.nodes.append(_GradNode(id(tgt), [id(p) for p in inputs], [id(g) for g in grad_vars], len(prog.nodes)))
    prog._fetch_cache.clear()
    return grad_vars


def forward_gradients(targets, inputs, input_gradients=None):
    """Forward-mode grad vars of targets w.r.t. inputs over the captured
    program (the machinery behind paddle.incubate.autograd.forward_grad;
    reference primapi.py:25). input_gradients are the input tangents
    (default: ones). Returns one grad var per target."""
    prog = default_main_program()
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if input_gradients is not None:
        tg = input_gradients if isinstance(input_gradients, (list, tuple)) else [input_gradients]
        if len(tg) != len(inputs):
            raise ValueError(f"{len(tg)} input_gradients for {len(inputs)} inputs")
        tangent_ids = [id(t) if t is not None else None for t in tg]
        for t in tg:
            if t is not None:
                prog._register(t)
    else:
        tangent_ids = [None] * len(inputs)
    jvp_vars = []
    for t in targets:
        g = Tensor(jnp.zeros_like(t._value))
        g.name = f"{getattr(t, 'name', 'out')}@FWDGRAD"
        prog._register(g)
        jvp_vars.append(g)
    prog.nodes.append(_JvpNode([id(t) for t in targets], [id(p) for p in inputs],
                               tangent_ids, [id(g) for g in jvp_vars], len(prog.nodes)))
    prog._fetch_cache.clear()
    return jvp_vars


def append_optimizer(optimizer, params_and_grads):
    """Record the optimizer-update node (used by Optimizer.minimize in static
    mode — the analog of appending sgd/adam ops to the program)."""
    prog = default_main_program()
    param_ids = [id(p) for p, _ in params_and_grads]
    grad_ids = [id(g) for _, g in params_and_grads]
    prog.nodes.append(_UpdateNode(param_ids, grad_ids, optimizer, {id(p): p for p, _ in params_and_grads}))
    prog._fetch_cache.clear()


# ---- scope ----
class _VarView:
    def __init__(self, t: Tensor):
        self._t = t

    def get_tensor(self):
        return np.asarray(self._t._value)

    def set(self, value, place=None):
        self._t._set_value_raw(jnp.asarray(value))


class Scope:
    """Hierarchical variable scope (reference phi/core Scope,
    framework/scope.h): `var` creates in THIS scope, `find_var` searches
    this scope then walks the PARENT chain — plus the program-variable
    lookup the TPU executor keeps (programs own the live tensors here).
    `new_scope` makes a kid; `drop_kids` releases the subtree."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._extra = {}
        self._parent = parent
        self._kids: List["Scope"] = []

    def parent(self) -> Optional["Scope"]:
        return self._parent

    def new_scope(self) -> "Scope":
        kid = Scope(parent=self)
        self._kids.append(kid)
        return kid

    def kids(self) -> List["Scope"]:
        return list(self._kids)

    def drop_kids(self):
        self._kids.clear()

    def local_var_names(self) -> List[str]:
        return list(self._extra)

    def find_var_locally(self, name):
        if name in self._extra:
            return _VarView(self._extra[name])
        return None

    def find_var(self, name):
        local = self.find_var_locally(name)
        if local is not None:
            return local
        if self._parent is not None:
            found = self._parent.find_var(name)
            if found is not None:
                return found
        for prog in [default_main_program(), _default_startup]:
            try:
                return _VarView(prog.var(name))
            except KeyError:
                continue
        return None

    def var(self, name):
        if name in self._extra:
            return _VarView(self._extra[name])
        t = Tensor(jnp.zeros(()))
        t.name = name
        self._extra[name] = t
        return _VarView(t)


_global_scope = Scope()
_scope_stack: List[Scope] = []


def global_scope() -> Scope:
    return _scope_stack[-1] if _scope_stack else _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


# ---- Executor ----
class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program: Program = None, feed: dict = None, fetch_list=None, scope=None, return_numpy: bool = True):
        prog = program if isinstance(program, Program) else getattr(program, "_program", None) or default_main_program()
        feed = feed or {}
        env: Dict[int, jnp.ndarray] = {}
        for name, value in feed.items():
            ph = prog.placeholders.get(name)
            if ph is None:
                raise KeyError(f"feed target '{name}' is not a placeholder of this program")
            arr = value._value if isinstance(value, Tensor) else jnp.asarray(np.asarray(value))
            env[id(ph)] = arr
        _replay(prog, env)
        if fetch_list is None:
            return None
        results = []
        for f in fetch_list:
            tid = id(f) if isinstance(f, Tensor) else id(prog.var(f))
            v = env.get(tid)
            if v is None:
                v = prog.tensors[tid]._value
            results.append(np.asarray(v) if return_numpy else Tensor(v))
        return results

    def close(self):
        pass


# ---- misc static API ----
class BuildStrategy:
    def __init__(self):
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_optimizer_ops = False
        self.fuse_elewise_add_act_ops = False
        self.build_cuda_graph = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """XLA compiles the replay at Executor.run; this is a labeled wrapper."""

    def __init__(self, program, build_strategy: BuildStrategy = None):
        self._program = program if isinstance(program, Program) else program
        self.build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, *a, **k):
        return self


@contextlib.contextmanager
def name_scope(prefix: str):
    from ..utils import unique_name

    with unique_name.guard(prefix + "/"):
        yield


@contextlib.contextmanager
def device_guard(device: str = None):
    yield  # placement is XLA's decision on TPU


def cpu_places(device_count=None):
    from ..core.place import CPUPlace

    return [CPUPlace()] * (device_count or 1)


def cuda_places(device_ids=None):
    from ..core.place import CUDAPlace

    ids = device_ids if device_ids is not None else [0]
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    from ..core.place import XPUPlace

    ids = device_ids if device_ids is not None else [0]
    return [XPUPlace(i) for i in ids]


Variable = Tensor


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    from ..core.dtype import to_jax_dtype

    t = Tensor(jnp.full(tuple(shape), value, to_jax_dtype(dtype)))
    t.name = name or f"global_var_{len(default_main_program().tensors)}"
    t.persistable = persistable
    default_main_program()._register(t)
    global_scope()._extra[t.name] = t  # reference: global vars live in the scope
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None):
    from ..ops.compat import create_parameter as _cp

    p = _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias, default_initializer=default_initializer)
    default_main_program()._register(p)
    if name:
        global_scope()._extra[name] = p
    return p


def Print(input, first_n=-1, message=None, summarize=20, **kwargs):
    """Debug print op (reference static.Print): eager host print at replay."""
    from ..ops._dispatch import apply, as_tensor

    def f(v):
        jax.debug.print((message or "") + " {}", v)
        return v

    return apply("static_print", f, as_tensor(input))


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Wrap a host python function as an op (reference static.py_func) via
    jax.pure_callback."""
    from ..ops._dispatch import apply, as_tensor

    xs = [as_tensor(t) for t in (x if isinstance(x, (list, tuple)) else [x])]
    multi = isinstance(out, (list, tuple))
    outs = list(out) if multi else [out]
    shapes = tuple(jax.ShapeDtypeStruct(tuple(o.shape), o._value.dtype) for o in outs)

    def host(*a):
        res = func(*[Tensor(jnp.asarray(v)) for v in a])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r._value if isinstance(r, Tensor) else r) for r in res)

    def f(*vals):
        res = jax.pure_callback(host, shapes, *vals)
        return tuple(res) if multi else res[0]

    return apply("py_func", f, *xs)


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    from ..metric import Auc

    m = Auc(num_thresholds=num_thresholds)
    import numpy as _np

    preds = _np.asarray(input._value)
    if preds.ndim == 1:
        preds = _np.stack([1 - preds, preds], -1)
    m.update(preds, _np.asarray(label._value))
    val = m.accumulate()
    return Tensor(jnp.asarray(val, jnp.float32)), None, None


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from ..optimizer.lr import ExponentialDecay

    return ExponentialDecay(learning_rate=learning_rate, gamma=decay_rate)


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR metrics (reference static.ctr_metric_bundle): returns (auc, batch_auc)
    style tensors computed eagerly."""
    a, _, _ = auc(input, label)
    return a, a


# ---- program (de)serialization ----
def serialize_program(feed_vars, fetch_vars, **kwargs) -> bytes:
    import pickle

    prog = default_main_program()
    payload = {
        "placeholders": {n: (list(t.shape), str(t.dtype)) for n, t in prog.placeholders.items()},
        "n_ops": len(prog.nodes),
    }
    return pickle.dumps(payload)


def serialize_persistables(feed_vars, fetch_vars, **kwargs) -> bytes:
    import pickle

    prog = default_main_program()
    state = {k: np.asarray(v._value) for k, v in prog.state_dict().items()}
    return pickle.dumps(state)


def deserialize_program(data: bytes):
    import pickle

    return pickle.loads(data)


def deserialize_persistables(program, data: bytes, executor=None):
    import pickle

    state = pickle.loads(data)
    if isinstance(program, Program):
        program.set_state_dict({k: Tensor(jnp.asarray(v)) for k, v in state.items()})
    return state


def save_to_file(path: str, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    return program


def save(program, model_path, protocol=4, **configs):
    import pickle

    state = {k: np.asarray(v._value) for k, v in program.state_dict().items()}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    import pickle

    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    program.set_state_dict({k: Tensor(jnp.asarray(v)) for k, v in state.items()})


def load_program_state(model_path, var_list=None):
    import pickle

    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    program.set_state_dict({k: Tensor(jnp.asarray(v)) for k, v in state_dict.items()})


# ---- EMA ----
class ExponentialMovingAverage:
    """EMA over trainable params (reference static.ExponentialMovingAverage):
    update() after each step; apply()/restore() swap params for eval."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema: Dict[int, jnp.ndarray] = {}
        self._backup: Dict[int, jnp.ndarray] = {}
        self._step = 0

    def update(self):
        self._step += 1
        for p in default_main_program().all_parameters():
            if p.stop_gradient:
                continue
            cur = self._ema.get(id(p))
            v = p._value
            self._ema[id(p)] = v if cur is None else self._decay * cur + (1 - self._decay) * v

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        params = [p for p in default_main_program().all_parameters() if id(p) in self._ema]
        self._backup = {id(p): p._value for p in params}
        bias_fix = 1 - self._decay ** max(self._step, 1)
        for p in params:
            p._set_value_raw(self._ema[id(p)] / bias_fix)
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        for p in default_main_program().all_parameters():
            if id(p) in self._backup:
                p._set_value_raw(self._backup[id(p)])
        self._backup = {}


# ---- ParamAttr variants / IPU gates ----
from ..param_attr import ParamAttr


class WeightNormParamAttr(ParamAttr):
    """Weight-normalized parameter attr (reference WeightNormParamAttr); the
    dim argument records the norm axis for layers that implement it."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim


def _ipu_unsupported(*a, **k):
    raise RuntimeError("IPU support is not available in the TPU build")


class IpuStrategy:
    def __init__(self):
        _ipu_unsupported()


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        _ipu_unsupported()


def ipu_shard_guard(*a, **k):
    _ipu_unsupported()


def set_ipu_shard(*a, **k):
    _ipu_unsupported()
