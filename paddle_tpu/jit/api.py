"""paddle.jit — dy2static (jit/api.py:232 to_static, :792 save, :1274 load).

The reference converts Python AST into ProgramDesc; on TPU jax.jit IS the
converter (trace once, compile). `to_static` wraps a function/Layer method in
a cached jit with the tape disabled inside; `save` exports the traced
program as serialized StableHLO (weights baked, jax.export) + a state_dict;
`load` rebuilds a TranslatedLayer executing the deserialized artifact —
runnable without the original Python class, the TranslatedLayer contract.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from ..core import random as _random
from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..observability import instrument as _obs
from ..observability import metrics as _obs_metrics
from ..static.input_spec import InputSpec

_MODEL_SUFFIX = ".pdmodel"
_PARAMS_SUFFIX = ".pdiparams"
_META_SUFFIX = ".pdmeta"


def _leaf_to_raw(v):
    if isinstance(v, Tensor):
        return v._value
    return v


def _is_arraylike(v):
    return isinstance(v, (Tensor, np.ndarray, jnp.ndarray, float, int, bool)) or hasattr(v, "__jax_array__")


class StaticFunction:
    """to_static-wrapped callable: jit cache + original-fn access (parity with
    dy2static StaticFunction: .code/.concrete_program reduced to the jaxpr)."""

    def __init__(self, function, input_spec=None, build_strategy=None, backend=None, **kwargs):
        functools.update_wrapper(self, function)
        self._function = function
        self._input_spec = input_spec
        self._layer = None  # bound Layer for methods
        self._jit_cache = {}

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = StaticFunction(self._function.__get__(instance, owner), self._input_spec)
        bound._layer = instance
        # cache the bound wrapper on the instance so the jit cache persists
        name = self._function.__name__
        instance.__dict__[name] = bound
        return bound

    @property
    def dygraph_function(self):
        return self._function

    @property
    def code(self):
        """Transformed source when AST conversion ran (dy2static .code parity)."""
        fn = getattr(self, "_converted", None) or self._function
        fn = getattr(fn, "__func__", fn)
        src = getattr(fn, "_dy2static_source", None)
        if src is not None:
            return src
        import inspect

        try:
            return inspect.getsource(fn)
        except (OSError, TypeError):
            return None

    def _traced(self, layer, n_args):
        key = ("layer", n_args) if layer is not None else ("fn", n_args)
        if key in self._jit_cache:
            if _obs_metrics.enabled():
                _obs.record_compile("to_static", cache_hit=True)
            return self._jit_cache[key]
        if _obs_metrics.enabled():
            _obs.record_compile("to_static", cache_hit=False)
        # trace the AST-converted variant when one exists; the ORIGINAL
        # function stays in self._function for eager fallback / parity APIs
        fn = getattr(self, "_converted", None) or self._function

        if layer is not None:
            # inline the functional_call overlay but invoke the ORIGINAL
            # function (layer.forward may now BE this StaticFunction)
            def traced(params, buffers, seed, *raw_args):
                from ..core import functional as F

                uid_map = {}
                buf_name = {}
                for name, p in layer.named_parameters():
                    if name in params:
                        uid_map[p._uid] = params[name]
                for name, b in layer.named_buffers():
                    if b is not None and name in buffers:
                        uid_map[b._uid] = buffers[name]
                        buf_name[b._uid] = name
                with F.overlay(uid_map), no_grad(), _random.rng_scope(seed):
                    out = fn(*[Tensor(a) for a in raw_args])
                    new_buffers = {buf_name[uid]: val for uid, val in uid_map.items() if uid in buf_name}
                return jax.tree_util.tree_map(_leaf_to_raw, out), new_buffers

            jitted = jax.jit(traced)
        else:

            def traced(seed, *raw_args):
                with no_grad(), _random.rng_scope(seed):
                    out = fn(*[Tensor(a) for a in raw_args])
                return jax.tree_util.tree_map(_leaf_to_raw, out)

            jitted = jax.jit(traced)
        if _obs_metrics.enabled():
            jitted = _obs.TimedFirstCall(jitted, "to_static")
        self._jit_cache[key] = jitted
        return jitted

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled():
            return self._function(*args, **kwargs)
        if kwargs or not all(_is_arraylike(a) for a in args):
            # non-array args force the eager path (still correct, not cached)
            return self._function(*args, **kwargs)
        if any(isinstance(getattr(a, "_value", a), jax.core.Tracer) for a in args):
            # already under a trace: inline (converted variant if one exists,
            # so control flow compiles instead of raising in the outer trace)
            return (getattr(self, "_converted", None) or self._function)(*args, **kwargs)
        if getattr(self, "_eager_fallback", False):
            return self._function(*args, **kwargs)
        raw = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        self._seed_counter = getattr(self, "_seed_counter", 0) + 1
        seed = jnp.uint32(self._seed_counter)
        try:
            if self._layer is not None:
                params, buffers = self._layer.functional_state()
                jitted = self._traced(self._layer, len(raw))
                out, new_buffers = jitted(params, buffers, seed, *raw)
                named = dict(self._layer.named_buffers())
                for name, val in new_buffers.items():
                    if name in named and named[name] is not None:
                        named[name]._set_value_raw(val)
            else:
                jitted = self._traced(None, len(raw))
                out = jitted(seed, *raw)
        except (
            jax.errors.TracerBoolConversionError,
            jax.errors.TracerArrayConversionError,
            jax.errors.TracerIntegerConversionError,
            jax.errors.ConcretizationTypeError,
        ):
            # data-dependent python control flow: rewrite the AST into
            # convert-calls (lax.while_loop / select) like the reference's
            # dy2static transformers, then retry the trace
            if not getattr(self, "_ast_tried", False):
                self._ast_tried = True
                try:
                    from .dy2static import convert_to_static

                    self._converted = convert_to_static(self._function)
                    self._jit_cache.clear()
                    return self.__call__(*args, **kwargs)
                except Exception:
                    self._converted = None
            # conversion unavailable/failed: eager execution (correct,
            # uncompiled) — cached so we don't re-trace every call
            import warnings

            warnings.warn(
                f"to_static: '{getattr(self._function, '__name__', '?')}' uses "
                "data-dependent Python control flow that could not be "
                "AST-converted; falling back to eager execution",
                stacklevel=2,
            )
            self._eager_fallback = True
            self._jit_cache.clear()
            return self._function(*args, **kwargs)
        return jax.tree_util.tree_map(
            lambda v: Tensor(v) if isinstance(v, jnp.ndarray) else v, out
        )

    def concrete_program(self, *args):
        raw = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        if self._layer is not None:
            params, buffers = self._layer.functional_state()
            return self._traced(self._layer, len(raw)).lower(params, buffers, jnp.uint32(0), *raw)
        return self._traced(None, len(raw)).lower(jnp.uint32(0), *raw)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """Decorator: compile a function or Layer.forward via jax.jit."""

    def decorate(fn):
        from ..nn.layer.layers import Layer

        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec=input_spec)
            sf._layer = fn
            fn.forward = sf
            fn._to_static_spec = input_spec
            return fn
        return StaticFunction(fn, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(function):
    """Mark a function to stay eager (dy2static skip-list analog)."""
    function._not_to_static = True
    return function


def ignore_module(modules):
    """Parity no-op: jax tracing has no module skip list."""
    return None


# ---------------- save / load ----------------
def _specs_from(input_spec, layer):
    if input_spec is None:
        input_spec = getattr(layer, "_to_static_spec", None)
    if input_spec is None:
        raise ValueError("paddle.jit.save needs input_spec (list of InputSpec or example Tensors)")
    specs = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            specs.append(s)
        elif isinstance(s, Tensor):
            specs.append(InputSpec.from_tensor(s))
        else:
            a = np.asarray(s)
            specs.append(InputSpec.from_numpy(a))
    return specs


def _sds_of(spec: InputSpec, scope):
    dims = []
    sym = []
    for i, d in enumerate(spec.shape):
        if d is None or (isinstance(d, int) and d < 0):
            dims.append(f"b{len(sym)}")
            sym.append(dims[-1])
        else:
            dims.append(str(d))
    if sym:
        shape = jax_export.symbolic_shape(",".join(dims), scope=scope)
    else:
        shape = tuple(int(d) for d in spec.shape)
    return jax.ShapeDtypeStruct(shape, spec._np_dtype())


def save(layer, path, input_spec=None, **configs):
    """Export layer.forward at `input_spec` to serialized StableHLO (+ params).

    Files: {path}.pdmodel (portable program, weights baked),
    {path}.pdiparams (state_dict for re-training), {path}.pdmeta (signature).
    """
    from ..framework import io as fio

    specs = _specs_from(input_spec, layer)
    layer.eval()
    params, buffers = layer.functional_state()
    # export must trace the original forward, not a to_static wrapper
    sf = layer.forward if isinstance(getattr(layer, "forward", None), StaticFunction) else None
    if sf is not None:
        layer.forward = sf._function
    try:

        def fwd(*raw_args):
            with no_grad(), _random.rng_scope(jnp.uint32(0)):
                out, _ = layer.functional_call(params, buffers, *[Tensor(a) for a in raw_args])
            return jax.tree_util.tree_map(_leaf_to_raw, out)

        scope = jax_export.SymbolicScope()
        sds = [_sds_of(s, scope) for s in specs]
        exported = jax_export.export(jax.jit(fwd))(*sds)
        blob = exported.serialize()
    finally:
        if sf is not None:
            layer.forward = sf

    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path + _MODEL_SUFFIX, "wb") as f:
        f.write(blob)
    fio.save(layer.state_dict(), path + _PARAMS_SUFFIX)
    meta = {
        "input_specs": [{"shape": [d if d is None else int(d) for d in s.shape], "dtype": s.dtype, "name": s.name} for s in specs],
        "format": "stablehlo-jax-export-v1",
    }
    with open(path + _META_SUFFIX, "w") as f:
        json.dump(meta, f)


class TranslatedLayer:
    """Deserialized inference program (jit/translated_layer.py analog): call
    it like the original Layer; weights are baked into the program."""

    def __init__(self, exported, state_dict, meta):
        self._exported = exported
        self._state_dict = state_dict
        self._input_specs = meta["input_specs"]
        self._call = exported.call

    def __call__(self, *args):
        raw = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        out = self._call(*raw)
        return jax.tree_util.tree_map(lambda v: Tensor(v) if isinstance(v, jnp.ndarray) else v, out)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only (weights are baked into the program)")

    def state_dict(self):
        return dict(self._state_dict)

    def parameters(self):
        return [Tensor(np.asarray(v)) for v in self._state_dict.values()]


def load(path, **configs) -> TranslatedLayer:
    from ..framework import io as fio

    with open(path + _MODEL_SUFFIX, "rb") as f:
        exported = jax_export.deserialize(f.read())
    state = fio.load(path + _PARAMS_SUFFIX) if os.path.exists(path + _PARAMS_SUFFIX) else {}
    with open(path + _META_SUFFIX) as f:
        meta = json.load(f)
    return TranslatedLayer(exported, state, meta)


_CODE_LEVEL = 0
_VERBOSITY = 0
_TO_STATIC_ENABLED = True


def set_code_level(level=100, also_to_stdout=False):
    """Reference: jit/dy2static logging of transformed code. The TPU build has
    no AST transforms; the analog prints the StableHLO of traced functions at
    level>0 (stored for introspection)."""
    global _CODE_LEVEL
    _CODE_LEVEL = level


def set_verbosity(level=0, also_to_stdout=False):
    global _VERBOSITY
    _VERBOSITY = level


def enable_to_static(enable_to_static_bool=True):
    """Globally toggle @to_static (reference ProgramTranslator.enable):
    when off, decorated functions run eagerly."""
    global _TO_STATIC_ENABLED
    _TO_STATIC_ENABLED = bool(enable_to_static_bool)


def _to_static_enabled() -> bool:
    return _TO_STATIC_ENABLED
