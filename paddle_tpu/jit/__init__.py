from .api import TranslatedLayer, ignore_module, load, not_to_static, save, to_static

__all__ = ["to_static", "not_to_static", "save", "load", "TranslatedLayer", "ignore_module"]
