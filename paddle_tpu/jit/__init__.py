from .api import (TranslatedLayer, enable_to_static, ignore_module, load, not_to_static, save, set_code_level, set_verbosity, to_static)

__all__ = ["to_static", "not_to_static", "save", "load", "TranslatedLayer", "ignore_module", "set_code_level", "set_verbosity", "enable_to_static"]
