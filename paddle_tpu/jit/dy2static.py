"""dy2static control-flow conversion (python/paddle/jit/dy2static analog:
ifelse_transformer.py, loop_transformer.py, logical_transformer.py,
convert_operators.py — ~3.7k LoC of AST rewriting in the reference).

TPU re-design: the AST transform rewrites data-dependent `if`/`while`/
`for range()` into *convert calls* that decide at RUNTIME whether the
governing value is traced:

- python value   -> ordinary python control flow (zero overhead, exact
                    semantics, unrolling under jit stays available)
- traced tracer  -> `lax.while_loop` for loops; both-branches + select for
                    `if` (what XLA lowers small conditionals to anyway, and
                    it sidesteps pytree/registration issues for Tensor
                    carries)

This mirrors the reference's convert_ifelse/convert_while_loop runtime
(jit/dy2static/convert_operators.py) rather than trying to prove tracedness
statically. Variables assigned inside a branch/loop are carried explicitly;
possibly-undefined names are guarded with an UNDEFINED sentinel (the
reference's UndefinedVar)."""

from __future__ import annotations

import ast
import inspect
import textwrap
import types
from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor

__all__ = [
    "UNDEFINED", "convert_ifelse", "convert_while", "convert_for_range",
    "convert_and", "convert_or", "convert_not", "convert_to_static",
    "TransformError",
]


class TransformError(RuntimeError):
    pass


class _Undefined:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<UNDEFINED>"

    def __bool__(self):
        raise NameError("variable used before assignment in converted control flow")


UNDEFINED = _Undefined()


def _raw(v):
    return v._value if isinstance(v, Tensor) else v


def _is_traced(v) -> bool:
    return isinstance(_raw(v), jax.core.Tracer)


def _scalar_bool(raw_cond):
    c = jnp.squeeze(jnp.asarray(raw_cond))
    if c.ndim != 0:
        raise TransformError(
            f"condition must be a scalar (or one-element) tensor, got shape {c.shape}")
    return c.astype(bool)


# ---------------- runtime convert calls ----------------
def _try_lax_cond(c, true_fn, false_fn, init_vars):
    """Real conditional via lax.cond: only the taken branch EXECUTES at
    runtime, so guarded expressions (`if x > 0: y = 1 / x`) cannot poison
    outputs or gradients with the untaken branch's inf/NaN (the where-NaN
    hazard of the select fallback). Both branches are still TRACED, so
    Python side effects in either run at trace time — same as the select
    path. Requires matching array carries across branches; any structural
    mismatch raises and the caller falls back to the select form."""
    is_arr = [isinstance(_raw(v), (jax.Array, jax.core.Tracer)) or isinstance(v, Tensor)
              for v in init_vars]
    operand = tuple(jnp.asarray(_raw(v)) for v, a in zip(init_vars, is_arr) if a)

    def rebuild(op):
        it = iter(op)
        out = []
        for v, a in zip(init_vars, is_arr):
            if not a:
                out.append(v)
            else:
                leaf = next(it)
                out.append(Tensor(leaf) if isinstance(v, Tensor) else leaf)
        return tuple(out)

    metas = {}

    def wrap(fn, tag):
        def wrapped(op):
            outs = fn(rebuild(op))
            arrs, meta = [], []
            for o in outs:
                r = _raw(o)
                if isinstance(r, (jax.Array, jax.core.Tracer)):
                    arrs.append(r)
                    meta.append(("arr", isinstance(o, Tensor)))
                else:
                    meta.append(("static", o))
            metas[tag] = meta
            return tuple(arrs)

        return wrapped

    # abstract compatibility probe FIRST (jax.eval_shape stages nothing):
    # a mismatch must not leave an abandoned lax.cond — with both branches'
    # staged effects like jax.debug.print — in the ambient trace when the
    # caller falls back to the select form
    t_avals = jax.eval_shape(wrap(true_fn, "t"), operand)
    f_avals = jax.eval_shape(wrap(false_fn, "f"), operand)
    tm, fm = metas["t"], metas["f"]
    if len(tm) != len(fm):
        raise TransformError("branch output arity mismatch")
    for (tk, tv), (fk, fv) in zip(tm, fm):
        if tk != fk:
            raise TransformError("mixed array/static carry across branches")
        if tk == "static":
            same = tv is fv
            if not same:
                try:
                    same = bool(tv == fv)
                except Exception:
                    same = False
            if not same:
                raise TransformError("static carry differs across branches")
    if [(a.shape, a.dtype) for a in t_avals] != [(a.shape, a.dtype) for a in f_avals]:
        raise TransformError("array carry shape/dtype differs across branches")

    res = jax.lax.cond(c, wrap(true_fn, "t"), wrap(false_fn, "f"), operand)
    out, it = [], iter(res)
    for (tk, tv), (fk, fv) in zip(metas["t"], metas["f"]):
        if tk == "arr":
            leaf = next(it)
            out.append(Tensor(leaf) if (tv or fv) else leaf)
        else:
            out.append(tv)
    return tuple(out)


def convert_ifelse(cond, true_fn: Callable, false_fn: Callable, init_vars: tuple,
                   names: Sequence[str] = ()):
    """if/else convert call (reference convert_ifelse). Traced cond: first
    try a REAL conditional (lax.cond — runtime-exclusive branches, see
    _try_lax_cond); carries lax.cond can't express (mixed array/static,
    UNDEFINED-in-one-branch, differing statics) fall back to running both
    branches under the ambient trace and selecting per variable, where the
    precise user-facing errors are raised."""
    if not _is_traced(cond):
        taken = true_fn if bool(_raw(cond)) else false_fn
        return taken(init_vars)
    c = _scalar_bool(_raw(cond))
    try:
        return _try_lax_cond(c, true_fn, false_fn, init_vars)
    except (TransformError, TypeError, ValueError):
        pass
    t_out = true_fn(init_vars)
    f_out = false_fn(init_vars)
    out = []
    for i, (tv, fv) in enumerate(zip(t_out, f_out)):
        name = names[i] if i < len(names) else f"#{i}"
        if tv is UNDEFINED and fv is UNDEFINED:
            out.append(UNDEFINED)
            continue
        if tv is UNDEFINED or fv is UNDEFINED:
            raise TransformError(
                f"variable '{name}' is assigned in only one branch of a "
                "traced if/else and has no prior value; initialize it before "
                "the if")
        tr, fr = _raw(tv), _raw(fv)
        if isinstance(tr, (jax.Array, jax.core.Tracer)) or isinstance(fr, (jax.Array, jax.core.Tracer)) \
                or isinstance(tr, (int, float, bool)) or isinstance(fr, (int, float, bool)):
            try:
                sel = jnp.where(c, tr, fr)
            except Exception as e:
                raise TransformError(
                    f"variable '{name}' has incompatible values across traced "
                    f"if/else branches: {e}") from e
            out.append(Tensor(sel) if isinstance(tv, Tensor) or isinstance(fv, Tensor) else sel)
        else:
            if tr is not fr and tr != fr:
                raise TransformError(
                    f"non-tensor variable '{name}' differs across traced "
                    f"if/else branches ({tr!r} vs {fr!r}); this cannot compile")
            out.append(tv)
    return tuple(out)


def _resolve_undefined(init_vars, names, probe_fn):
    """Body-local loop vars (assigned before read inside the body, e.g.
    `m = scores.max()`) reach the carry as UNDEFINED. Probe one body
    iteration in the ambient trace to learn each slot's aval and seed the
    carry with zeros of that aval: the probe's outputs are dead code XLA
    DCEs, and genuinely read-before-assign vars fail inside the probe with
    a clear error (the reference's UndefinedVar checks)."""
    if not any(v is UNDEFINED for v in init_vars):
        return init_vars
    undef_names = [names[i] if i < len(names) else f"#{i}"
                   for i, v in enumerate(init_vars) if v is UNDEFINED]
    try:
        probed = probe_fn(init_vars)
    except TransformError:
        raise
    except Exception as e:
        raise TransformError(
            f"loop variable(s) {undef_names} have no value before a traced "
            f"loop and appear to be read before assignment inside it: {e}") from e
    out = list(init_vars)
    for i, v in enumerate(init_vars):
        if v is not UNDEFINED:
            continue
        pv = probed[i]
        if pv is UNDEFINED:
            name = names[i] if i < len(names) else f"#{i}"
            raise TransformError(
                f"loop variable '{name}' is never assigned a traceable value "
                "in the loop body; initialize it before the loop")
        r = jnp.zeros_like(jnp.asarray(_raw(pv)))
        out[i] = Tensor(r) if isinstance(pv, Tensor) else r
    return tuple(out)


def convert_while(test_fn: Callable, body_fn: Callable, init_vars: tuple,
                  names: Sequence[str] = ()):
    """while convert call: python loop when the condition is concrete,
    lax.while_loop when traced (reference convert_while_loop)."""
    first = test_fn(init_vars)
    if not _is_traced(first) and not any(_is_traced(v) for v in init_vars):
        # reuse `first` for iteration 0 — re-evaluating a stateful test would
        # diverge from eager semantics
        vars_ = init_vars
        cond = bool(_raw(first))
        while cond:
            vars_ = body_fn(vars_)
            cond = bool(_raw(test_fn(vars_)))
        return vars_

    init_vars = _resolve_undefined(init_vars, names, body_fn)
    wrap = [isinstance(v, Tensor) for v in init_vars]

    def rewrap(raws):
        return tuple(Tensor(r) if w and not isinstance(r, Tensor) else r
                     for r, w in zip(raws, wrap))

    def cond(raws):
        return _scalar_bool(_raw(test_fn(rewrap(raws))))

    def body(raws):
        out = body_fn(rewrap(raws))
        return tuple(jnp.asarray(_raw(v)) for v in out)

    init = tuple(jnp.asarray(_raw(v)) for v in init_vars)
    try:
        final = lax.while_loop(cond, body, init)
    except TypeError as e:
        raise TransformError(
            f"traced while loop carry changed structure across iterations "
            f"(vars {tuple(names)}): {e}") from e
    return rewrap(final)


def convert_for_range(start, stop, step, body_fn: Callable, init_vars: tuple,
                      names: Sequence[str] = ()):
    """`for i in range(...)` convert call: python unrolled loop for concrete
    bounds, counter-carrying lax.while_loop for traced bounds. body_fn(i,
    vars) -> vars."""
    rs, re_, rp = _raw(start), _raw(stop), _raw(step)
    if not any(isinstance(b, jax.core.Tracer) for b in (rs, re_, rp)):
        vars_ = init_vars
        for i in range(int(rs), int(re_), int(rp)):
            vars_ = body_fn(i, vars_)
        return vars_

    init_vars = _resolve_undefined(init_vars, names,
                                   lambda vars_: body_fn(jnp.asarray(rs), vars_))
    wrap = [isinstance(v, Tensor) for v in init_vars]

    def rewrap(raws):
        return tuple(Tensor(r) if w and not isinstance(r, Tensor) else r
                     for r, w in zip(raws, wrap))

    step_arr = jnp.asarray(rp)

    def cond(carry):
        i = carry[0]
        return jnp.where(step_arr > 0, i < re_, i > re_)

    def body(carry):
        i, raws = carry[0], carry[1:]
        out = body_fn(i, rewrap(raws))
        return (i + step_arr,) + tuple(jnp.asarray(_raw(v)) for v in out)

    init = (jnp.asarray(rs),) + tuple(jnp.asarray(_raw(v)) for v in init_vars)
    try:
        final = lax.while_loop(cond, body, init)
    except TypeError as e:
        raise TransformError(
            f"traced for-range loop carry changed structure across iterations "
            f"(vars {tuple(names)}): {e}") from e
    return rewrap(final[1:])


def convert_and(lhs_fn: Callable, rhs_fn: Callable):
    """`a and b` preserving short-circuit for python values, jnp.logical_and
    for traced (reference logical_transformer)."""
    a = lhs_fn()
    if not _is_traced(a):
        return a if not bool(_raw(a)) else rhs_fn()
    b = rhs_fn()
    res = jnp.logical_and(_scalar_bool(_raw(a)), _scalar_bool(_raw(b)))
    return Tensor(res) if isinstance(a, Tensor) or isinstance(b, Tensor) else res


def convert_or(lhs_fn: Callable, rhs_fn: Callable):
    a = lhs_fn()
    if not _is_traced(a):
        return a if bool(_raw(a)) else rhs_fn()
    b = rhs_fn()
    res = jnp.logical_or(_scalar_bool(_raw(a)), _scalar_bool(_raw(b)))
    return Tensor(res) if isinstance(a, Tensor) or isinstance(b, Tensor) else res


def convert_not(v):
    if not _is_traced(v):
        return not bool(_raw(v))
    res = jnp.logical_not(_scalar_bool(_raw(v)))
    return Tensor(res) if isinstance(v, Tensor) else res


# ---------------- the AST transformer ----------------
_JST = "_paddle_jst"


def _names_assigned(stmts) -> List[str]:
    """Names assigned anywhere in stmts (not descending into nested defs)."""
    out = []

    def collect_target(t):
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect_target(e)
        elif isinstance(t, ast.Starred):
            collect_target(t.value)

    def walk(nodes):
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(node, (ast.Assign,)):
                for t in node.targets:
                    collect_target(t)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                collect_target(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                collect_target(node.target)
            elif isinstance(node, ast.withitem) and node.optional_vars is not None:
                collect_target(node.optional_vars)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
                    continue
                walk([child])

    walk(stmts)
    seen, uniq = set(), []
    for n in out:
        if n not in seen and not n.startswith("_pt_"):
            seen.add(n)
            uniq.append(n)
    return uniq


def _has_escape(stmts, *, top_loop=False) -> bool:
    """True if stmts contain return (any depth except nested defs), or
    break/continue not bound to an inner loop."""

    def walk(nodes, loop_depth):
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Return):
                return True
            if isinstance(node, (ast.Break, ast.Continue)) and loop_depth == 0:
                return True
            inner_depth = loop_depth + 1 if isinstance(node, (ast.For, ast.While, ast.AsyncFor)) else loop_depth
            for child in ast.iter_child_nodes(node):
                if walk([child], inner_depth):
                    return True
        return False

    return walk(stmts, 1 if top_loop else 0)


def _name(n, ctx):
    return ast.Name(id=n, ctx=ctx())


def _tuple_of(names, ctx):
    return ast.Tuple(elts=[_name(n, ctx) for n in names], ctx=ctx())


def _jst_attr(fn_name):
    return ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()), attr=fn_name, ctx=ast.Load())


def _undef_guard(name):
    """try: name\nexcept NameError: name = _paddle_jst.UNDEFINED"""
    return ast.Try(
        body=[ast.Expr(value=_name(name, ast.Load))],
        handlers=[ast.ExceptHandler(
            type=ast.Name(id="NameError", ctx=ast.Load()),
            name=None,
            body=[ast.Assign(targets=[_name(name, ast.Store)], value=_jst_attr("UNDEFINED"))],
        )],
        orelse=[], finalbody=[],
    )


def _make_branch_fn(fname, carried, body_stmts):
    args = ast.arguments(posonlyargs=[], args=[ast.arg(arg="_pt_vars")], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
    stmts = []
    if carried:
        stmts.append(ast.Assign(targets=[_tuple_of(carried, ast.Store)],
                                value=ast.Name(id="_pt_vars", ctx=ast.Load())))
    stmts.extend(body_stmts)
    stmts.append(ast.Return(value=_tuple_of(carried, ast.Load)))
    return ast.FunctionDef(name=fname, args=args, body=stmts, decorator_list=[], returns=None)


def _names_tuple_const(carried):
    return ast.Tuple(elts=[ast.Constant(value=n) for n in carried], ctx=ast.Load())


class _CtrlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._n = 0

    def _next(self):
        self._n += 1
        return self._n

    # -- boolean operators inside the function body --
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        # a and b and c -> convert_and(lambda: a, lambda: convert_and(...))
        fn = "convert_and" if isinstance(node.op, ast.And) else "convert_or"

        def lam(expr):
            return ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                                   kw_defaults=[], kwarg=None, defaults=[]),
                body=expr)

        result = node.values[-1]
        for val in reversed(node.values[:-1]):
            result = ast.Call(func=_jst_attr(fn), args=[lam(val), lam(result)], keywords=[])
        return result

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=_jst_attr("convert_not"), args=[node.operand], keywords=[])
        return node

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node
        carried = _names_assigned(node.body + node.orelse)
        i = self._next()
        tname, fname = f"_pt_true_{i}", f"_pt_false_{i}"
        stmts = [_undef_guard(n) for n in carried]
        stmts.append(_make_branch_fn(tname, carried, node.body))
        stmts.append(_make_branch_fn(fname, carried, node.orelse or [ast.Pass()]))
        call = ast.Call(
            func=_jst_attr("convert_ifelse"),
            args=[node.test, _name(tname, ast.Load), _name(fname, ast.Load),
                  _tuple_of(carried, ast.Load), _names_tuple_const(carried)],
            keywords=[])
        if carried:
            stmts.append(ast.Assign(targets=[_tuple_of(carried, ast.Store)], value=call))
        else:
            stmts.append(ast.Expr(value=call))
        return stmts

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_escape(node.body, top_loop=False):
            return node
        carried = _names_assigned(node.body)
        i = self._next()
        test_name, body_name = f"_pt_test_{i}", f"_pt_body_{i}"
        stmts = [_undef_guard(n) for n in carried]
        # test fn: unpack carry, return the condition
        args = ast.arguments(posonlyargs=[], args=[ast.arg(arg="_pt_vars")], vararg=None,
                             kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
        test_body = []
        if carried:
            test_body.append(ast.Assign(targets=[_tuple_of(carried, ast.Store)],
                                        value=ast.Name(id="_pt_vars", ctx=ast.Load())))
        test_body.append(ast.Return(value=node.test))
        stmts.append(ast.FunctionDef(name=test_name, args=args, body=test_body,
                                     decorator_list=[], returns=None))
        stmts.append(_make_branch_fn(body_name, carried, node.body))
        call = ast.Call(
            func=_jst_attr("convert_while"),
            args=[_name(test_name, ast.Load), _name(body_name, ast.Load),
                  _tuple_of(carried, ast.Load), _names_tuple_const(carried)],
            keywords=[])
        if carried:
            stmts.append(ast.Assign(targets=[_tuple_of(carried, ast.Store)], value=call))
        else:
            stmts.append(ast.Expr(value=call))
        return stmts

    def visit_For(self, node):
        self.generic_visit(node)
        if (node.orelse or _has_escape(node.body, top_loop=False)
                or not isinstance(node.target, ast.Name)
                or not (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range"
                        and not node.iter.keywords
                        and 1 <= len(node.iter.args) <= 3)):
            return node
        target = node.target.id
        carried = [n for n in _names_assigned(node.body) if n != target]
        rargs = node.iter.args
        if len(rargs) == 1:
            start, stop, step = ast.Constant(value=0), rargs[0], ast.Constant(value=1)
        elif len(rargs) == 2:
            start, stop, step = rargs[0], rargs[1], ast.Constant(value=1)
        else:
            start, stop, step = rargs
        i = self._next()
        body_name = f"_pt_forbody_{i}"
        stmts = [_undef_guard(n) for n in carried]
        args = ast.arguments(posonlyargs=[], args=[ast.arg(arg=target), ast.arg(arg="_pt_vars")],
                             vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
        fbody = []
        if carried:
            fbody.append(ast.Assign(targets=[_tuple_of(carried, ast.Store)],
                                    value=ast.Name(id="_pt_vars", ctx=ast.Load())))
        fbody.extend(node.body)
        fbody.append(ast.Return(value=_tuple_of(carried, ast.Load)))
        stmts.append(ast.FunctionDef(name=body_name, args=args, body=fbody,
                                     decorator_list=[], returns=None))
        call = ast.Call(
            func=_jst_attr("convert_for_range"),
            args=[start, stop, step, _name(body_name, ast.Load),
                  _tuple_of(carried, ast.Load), _names_tuple_const(carried)],
            keywords=[])
        if carried:
            stmts.append(ast.Assign(targets=[_tuple_of(carried, ast.Store)], value=call))
        else:
            stmts.append(ast.Expr(value=call))
        return stmts


def convert_to_static(fn: Callable) -> Callable:
    """AST-convert fn's data-dependent control flow into convert calls.

    Returns a new function with the same closure/globals. Raises
    TransformError when the source is unavailable or conversion fails."""
    if isinstance(fn, types.MethodType):
        converted = convert_to_static(fn.__func__)
        return types.MethodType(converted, fn.__self__)
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise TransformError(f"cannot get source of {fn!r}: {e}") from e
    tree = ast.parse(src)
    fndef = tree.body[0]
    if not isinstance(fndef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise TransformError(f"expected a function definition, got {type(fndef).__name__}")
    fndef.decorator_list = []  # strip @to_static etc. — we re-wrap ourselves
    _CtrlFlowTransformer().visit(fndef)

    freevars = fn.__code__.co_freevars
    if freevars:
        outer = ast.FunctionDef(
            name="_pt_outer",
            args=ast.arguments(posonlyargs=[], args=[ast.arg(arg=v) for v in freevars],
                               vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=[fndef, ast.Return(value=ast.Name(id=fndef.name, ctx=ast.Load()))],
            decorator_list=[], returns=None)
        module = ast.Module(body=[outer], type_ignores=[])
    else:
        module = ast.Module(body=[fndef], type_ignores=[])
    ast.fix_missing_locations(module)
    code = compile(module, filename=f"<dy2static {getattr(fn, '__qualname__', fn.__name__)}>",
                   mode="exec")
    from . import dy2static as _self

    ns = dict(fn.__globals__)
    ns[_JST] = _self
    exec(code, ns)
    if freevars:
        cells = [c.cell_contents for c in (fn.__closure__ or ())]
        new_fn = ns["_pt_outer"](*cells)
    else:
        new_fn = ns[fndef.name]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    new_fn._dy2static_source = ast.unparse(module)
    return new_fn
