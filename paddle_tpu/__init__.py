"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's capabilities.

Not a port: the reference's C++ PHI kernel library / executors / NCCL stack
(see /root/repo/SURVEY.md) is re-designed on jax/XLA/Pallas — ops lower to
StableHLO, the executor is XLA+PJRT, parallelism is GSPMD mesh sharding, and
hand-written kernels are Pallas. The public surface mirrors `import paddle`.
"""

from __future__ import annotations

from . import _jaxcompat  # noqa: F401  (backfills jax.shard_map & co. on 0.4.x)
from .version import full_version as __version__  # noqa: E402  (single source)

from .core import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    Tensor,
    bfloat16,
    bool_ as bool,  # noqa: A004
    complex64,
    complex128,
    device_count,
    enable_grad,
    float16,
    float32,
    float64,
    get_device,
    get_flags,
    get_rng_state,
    int8,
    int16,
    int32,
    int64,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    is_grad_enabled,
    no_grad,
    seed,
    set_device,
    set_flags,
    set_rng_state,
    to_tensor,
    uint8,
)
from .core.autograd import set_grad_enabled  # noqa: F401
from .core.dtype import DType as dtype  # noqa: F401
from .core.tensor import Parameter  # noqa: F401
from .ops import *  # noqa: F401,F403
from .ops import sum, max, min, all, any, abs, slice  # noqa: F401,A004
from .ops.logic import is_tensor  # noqa: F401
from .ops.compat import (  # noqa: F401
    LazyGuard,
    add_n,
    batch,
    check_shape,
    complex,
    create_parameter,
    disable_signal_handler,
    finfo,
    iinfo,
    increment,
    is_complex,
    is_floating_point,
    is_integer,
    nan_to_num,
    nanquantile,
    polar,
    rank,
    reverse,
    sgn,
    shape,
    shard_index,
    squeeze_,
    tanh_,
    tolist,
    unsqueeze_,
)
from .framework.random import get_cuda_rng_state, set_cuda_rng_state  # noqa: F401

# Subsystem namespaces land here as they are built out (nn, optimizer, io,
# distributed, jit, ...). Each addition extends this import block.
from . import autograd  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import nn  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from .distributed.parallel import DataParallel  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import data  # noqa: F401,E402
from . import framework  # noqa: F401,E402
from .framework.io import load, save  # noqa: F401,E402
from . import models  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import regularizer  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import hapi  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import ir  # noqa: F401,E402
from . import linalg  # noqa: F401,E402
from . import tensor  # noqa: F401,E402
from .core.selected_rows import SelectedRows  # noqa: F401,E402
from .core.string_tensor import StringTensor  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import observability  # noqa: F401,E402
from . import checkpoint  # noqa: F401,E402
from . import serving  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import fft  # noqa: F401,E402
from . import signal  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import audio  # noqa: F401,E402
from . import geometric  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import hub  # noqa: F401,E402
from . import version  # noqa: F401,E402


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Forward-FLOPs of a Layer (reference: python/paddle/hapi/dynamic_flops.py)."""
    from .utils.flops import dynamic_flops

    return dynamic_flops(net, input_size, custom_ops=custom_ops, print_detail=print_detail)
from .hapi import Model, summary  # noqa: F401,E402
from .hapi import callbacks  # noqa: F401,E402
from .param_attr import ParamAttr  # noqa: F401,E402

# paddle.grad
from .core.autograd import grad  # noqa: F401,E402
from .nn.layer.layers import disable_static, enable_static, in_dynamic_mode  # noqa: F401,E402


def get_default_dtype():
    from .core.flags import flag_value

    return flag_value("default_dtype")


def set_default_dtype(d):
    from .core.dtype import convert_dtype

    set_flags({"default_dtype": convert_dtype(d)})


def set_printoptions(**kwargs):
    import numpy as np

    np.set_printoptions(**{k: v for k, v in kwargs.items() if k in ("precision", "threshold", "edgeitems", "linewidth")})
