"""Global-batch assembly + async device feeding.

GSPMD's input contract: the training step consumes ONE global jax.Array
per field, sharded over the mesh's data axis; each host contributes only
the rows it loaded. This stage turns the packer's per-host numpy batches
into exactly that:

  * multi-process — ``jax.make_array_from_process_local_data`` assembles
    the global [B_global, S] array against a ``NamedSharding`` without any
    cross-host copy (data stays where it was read; the same primitive
    ``ShardedTrainStep._to_global_batch`` uses);
  * single process — ``jax.device_put`` against the batch sharding (or the
    default device), which is asynchronous: the transfer is in flight when
    the batch is handed over.

Layered under ``io.prefetch.DevicePrefetcher``: a producer thread runs
assembly (and therefore the host->device transfer) for batch k+1 while
the consumer runs step k, so the steady-state step never waits on infeed.
The consumer-side stall that remains is measured: ``host_wait_ms_mean``
(and the flag-gated ``data.host_wait_seconds`` histogram) is the time
``__next__`` blocked on the queue — the bench row's "host wait" number.

Checkpoint positioning: prefetch means the upstream stages run AHEAD of
the consumer. ``get_state()`` therefore does NOT read the live stage
state — the producer snapshots the pipeline state right after producing
each batch, and the feeder re-associates each snapshot with the batch as
it is yielded. The state you read after consuming batch k resumes at
batch k+1, regardless of how deep the prefetch queue is.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Iterator, Optional

import numpy as np

from .protocol import CheckpointableIterator, iterator_state, restore_iterator


def batch_sharding(mesh, batch_axes="dp"):
    """NamedSharding placing dim 0 of each batch field over the mesh's data
    axes (axis name or tuple of names, e.g. ("dp", "sharding"))."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if isinstance(batch_axes, str):
        batch_axes = (batch_axes,)
    missing = [a for a in batch_axes if a not in mesh.axis_names]
    if missing:
        raise ValueError(f"mesh {mesh.axis_names} has no axes {missing}")
    return NamedSharding(mesh, P(tuple(batch_axes)))


class GlobalBatchFeeder(CheckpointableIterator):
    """Iterate device-resident (optionally mesh-global) batches with
    transfer/compute overlap and exact checkpoint positioning.

    ``upstream`` is the host-batch iterator (usually a SequencePacker; any
    iterator of numpy pytrees works). ``sharding`` is a NamedSharding for
    the batch (see ``batch_sharding``); None feeds the default device.
    ``state_of``/``restore_to`` default to the upstream's own protocol
    methods and may be overridden to snapshot a larger pipeline.
    """

    def __init__(self, upstream: Iterator, sharding=None,
                 prefetch_depth: int = 2,
                 state_of: Optional[Callable] = None,
                 restore_to: Optional[Callable] = None):
        self.upstream = upstream
        self.sharding = sharding
        self.prefetch_depth = max(1, int(prefetch_depth))
        self._state_of = state_of or (lambda: iterator_state(self.upstream))
        self._restore_to = restore_to or (
            lambda s: restore_iterator(self.upstream, s))
        self._last_state = None
        # host-wait stats (consumer-side stalls)
        self.batches_fed = 0
        self.host_wait_s_total = 0.0

    # ---------------- assembly ----------------
    def _assemble(self, batch):
        import jax

        def put(leaf):
            v = np.asarray(leaf) if not isinstance(leaf, jax.Array) else leaf
            if self.sharding is not None and jax.process_count() > 1:
                return jax.make_array_from_process_local_data(
                    self.sharding, v)
            return jax.device_put(v, self.sharding)

        return jax.tree_util.tree_map(put, batch)

    # ---------------- iteration ----------------
    @property
    def host_wait_ms_mean(self) -> float:
        if not self.batches_fed:
            return 0.0
        return 1e3 * self.host_wait_s_total / self.batches_fed

    def __iter__(self):
        from ..io.prefetch import DevicePrefetcher
        from ..observability import metrics as _metrics

        pending = collections.deque()

        def produce():
            for host_batch in self.upstream:
                dev = self._assemble(host_batch)
                # snapshot AFTER producing: resuming from it starts at the
                # NEXT batch. append-then-yield keeps the deque in lockstep
                # with the prefetch queue (both FIFO, producer-ordered).
                pending.append(self._state_of())
                yield dev

        # depth batches ride the queue device-resident; device_put in
        # _assemble already ran in the producer thread, so _to_device's
        # second put is a no-op re-commit
        pre = iter(DevicePrefetcher(produce(), depth=self.prefetch_depth))
        while True:
            t0 = time.perf_counter()
            try:
                dev = next(pre)
            except StopIteration:
                return
            wait = time.perf_counter() - t0  # consumer stalled this long
            self._last_state = pending.popleft()
            self.batches_fed += 1
            self.host_wait_s_total += wait
            if _metrics.enabled():
                _metrics.histogram("data.host_wait_seconds", wait)
                _metrics.counter("data.batches", 1)
            yield dev

    # ---------------- protocol ----------------
    def get_state(self):
        """Pipeline state as of the last batch YIELDED to the consumer
        (not the producer's read-ahead position)."""
        if self._last_state is not None:
            return self._last_state
        return self._state_of()

    def set_state(self, state) -> None:
        self._restore_to(state)
        self._last_state = state
