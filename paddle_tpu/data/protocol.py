"""Checkpointable-iterator protocol for the input pipeline.

Every stage of the data pipeline (file-shard source, sequence packer,
device feeder, composed pipeline) implements the same two methods:

    get_state() -> dict      # JSON-plain: ints, strings, lists, dicts
    set_state(state) -> None # reposition so iteration resumes EXACTLY

The state a stage returns is everything needed to reproduce its future
output stream bit-for-bit: shard cursor + intra-shard offset + epoch for
sources, the partially-consumed document carry for the packer, the RNG
counter for anything stochastic. The composed pipeline state plugs
directly into ``TrainState.data_position`` and rides through
``checkpoint.CheckpointManager`` under the same atomic COMMIT as params
and optimizer state — a restored run continues the exact batch sequence
the interrupted one would have produced (the reference's reader-position
gap: its persistables/.pdopt/reader states were saved independently and
could resume out of sync).

States are deliberately JSON-plain (no arrays) so they also survive the
legacy pickle checkpoint path, `tools/data_inspect.py`, and manifest
embedding without array-shard machinery.

This module is numpy/stdlib-only (no jax import) so standalone tooling
can load it on machines without an accelerator runtime.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class CheckpointableIterator:
    """Base protocol: an iterator whose position is checkpointable."""

    def __iter__(self):
        return self

    def __next__(self):
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        raise NotImplementedError

    def set_state(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    # paddle-idiom aliases (DataLoader/nn.Layer use state_dict naming)
    def state_dict(self) -> Dict[str, Any]:
        return self.get_state()

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.set_state(state)


def iterator_state(obj) -> Optional[Dict[str, Any]]:
    """Best-effort state extraction from any pipeline-ish object: prefers
    the protocol's get_state, falls back to state_dict. None if the object
    carries no position (plain iterables)."""
    for name in ("get_state", "state_dict"):
        fn = getattr(obj, name, None)
        if callable(fn):
            try:
                return fn()
            except (TypeError, NotImplementedError):
                continue
    return None


def restore_iterator(obj, state) -> bool:
    """Counterpart of iterator_state: push `state` into obj via set_state /
    load_state_dict. Returns True if a restore method accepted it."""
    if state is None:
        return False
    for name in ("set_state", "load_state_dict"):
        fn = getattr(obj, name, None)
        if callable(fn):
            fn(state)
            return True
    return False


def mix_seed(*parts: int) -> int:
    """Deterministic seed mixing (splitmix64 finalizer) — decorrelates
    (seed, epoch, shard) tuples without the adjacent-seed correlation of
    plain addition. Pure function: resume recomputes the identical stream."""
    h = 0x9E3779B97F4A7C15
    for p in parts:
        h ^= (int(p) + 0x9E3779B97F4A7C15 + (h << 6) + (h >> 2)) & 0xFFFFFFFFFFFFFFFF
        h &= 0xFFFFFFFFFFFFFFFF
        h ^= h >> 30
        h = (h * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        h ^= h >> 27
        h = (h * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        h ^= h >> 31
    return h & 0xFFFFFFFF  # np.random.RandomState seed range
