"""Composed pipeline: source -> packer -> global-batch feeder, ONE state.

``DataPipeline`` ties the stages together and owns the composite
checkpoint state::

    {"version": 1, "epoch": e, "batches": n,
     "source": {...}, "packer": {...}}

which is exactly what ``TrainState.data_position`` stores. Saving it at
step k and restoring into a freshly-built pipeline replays the identical
packed-batch sequence from step k+1 — the mid-epoch-resume contract the
reference's reader position could not make (its dataset state lived
outside the checkpoint).

``build_pretrain_pipeline`` is the one-call constructor for the GPT
pretraining path: token shards -> per-host assignment -> packed [B, S]
-> mesh-global device batches.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .feed import GlobalBatchFeeder, batch_sharding
from .packing import SequencePacker
from .protocol import CheckpointableIterator, iterator_state, restore_iterator
from .sources import JsonlSource, TokenBinSource

_STATE_VERSION = 1


class DataPipeline(CheckpointableIterator):
    """source (+ packer) (+ feeder), iterated as one object.

    Iteration yields the outermost stage's batches (device batches when a
    feeder is attached, host numpy batches otherwise). ``get_state`` is
    positioned at the last batch the CONSUMER received even under
    prefetch — the feeder snapshots per batch (see feed.py).
    """

    def __init__(self, source, packer: Optional[SequencePacker] = None,
                 feeder: Optional[GlobalBatchFeeder] = None):
        self.source = source
        self.packer = packer
        self.feeder = feeder
        self._batches = 0
        if feeder is not None:
            # the feeder snapshots/ restores the WHOLE pipeline, not just
            # its immediate upstream
            feeder._state_of = self._stage_state
            feeder._restore_to = self._restore_stages

    # ---------------- composite state ----------------
    def _stage_state(self) -> Dict[str, Any]:
        state: Dict[str, Any] = {
            "version": _STATE_VERSION,
            "batches": self._batches,
        }
        src = iterator_state(self.source)
        if src is not None:
            state["source"] = src
            if "epoch" in src:
                state["epoch"] = src["epoch"]
        if self.packer is not None:
            state["packer"] = self.packer.get_state()
        return state

    def _restore_stages(self, state: Dict[str, Any]) -> None:
        if state.get("version", 1) != _STATE_VERSION:
            raise ValueError(
                f"data pipeline state version {state.get('version')!r} is "
                f"not {_STATE_VERSION}")
        self._batches = int(state.get("batches", 0))
        if "source" in state:
            restore_iterator(self.source, state["source"])
        if self.packer is not None and "packer" in state:
            self.packer.set_state(state["packer"])

    def get_state(self) -> Dict[str, Any]:
        if self.feeder is not None:
            return self.feeder.get_state()
        return self._stage_state()

    def set_state(self, state: Dict[str, Any]) -> None:
        if self.feeder is not None:
            self.feeder.set_state(state)
        else:
            self._restore_stages(state)

    # ---------------- elastic re-assignment ----------------
    def reassign(self, process_index: int, process_count: int,
                 peer_progress=None) -> "DataPipeline":
        """Adopt a new fleet identity mid-epoch (elastic shrink/grow):
        delegates to the source's ``reassign`` (exactly-once coverage
        re-validated there). The packer's in-flight carry is kept — those
        records were already drawn from the old assignment. Restart
        iteration (``iter(pipeline)``) after reassigning: any prefetched
        batches in a live feeder generator belong to the old world."""
        if not hasattr(self.source, "reassign"):
            raise TypeError(
                f"source {type(self.source).__name__} does not support "
                "elastic reassignment")
        self.source.reassign(process_index, process_count,
                             peer_progress=peer_progress)
        return self

    def shard_progress(self):
        if not hasattr(self.source, "shard_progress"):
            return None
        return self.source.shard_progress()

    # ---------------- stats passthrough ----------------
    @property
    def packing_efficiency(self) -> float:
        return self.packer.efficiency if self.packer is not None else 1.0

    @property
    def host_wait_ms_mean(self) -> float:
        return (self.feeder.host_wait_ms_mean
                if self.feeder is not None else 0.0)

    # ---------------- iteration ----------------
    def __iter__(self):
        stage = self.feeder or self.packer or self.source
        for batch in stage:
            self._batches += 1
            yield batch

    def __next__(self):  # pragma: no cover - iterate via __iter__
        raise TypeError("iterate DataPipeline with iter(), not next() "
                        "(prefetch state lives in the generator)")


def build_pretrain_pipeline(
        files, batch_size: int, seq_len: int, *,
        source_format: str = "bin", dtype: str = "uint16",
        eos_id: Optional[int] = None, chunk_len: Optional[int] = None,
        seed: int = 0, process_index: Optional[int] = None,
        process_count: Optional[int] = None, shuffle_shards: bool = True,
        shuffle_records: bool = False, repeat: bool = True,
        pad_id: int = 0, split_long_docs: bool = False,
        mesh=None, batch_axes="dp", prefetch_depth: int = 2,
        device_feed: bool = True) -> DataPipeline:
    """Token shards -> packed, device-fed pipeline in one call.

    ``batch_size`` is the PER-HOST batch; with a mesh spanning multiple
    processes the global batch is ``batch_size * process_count`` rows
    sharded over ``batch_axes``. Set ``device_feed=False`` for a host-only
    pipeline (tooling, tests, non-jax consumers).
    """
    if source_format == "bin":
        source = TokenBinSource(
            files, dtype=dtype, eos_id=eos_id, chunk_len=chunk_len,
            seed=seed, process_index=process_index,
            process_count=process_count, shuffle_shards=shuffle_shards,
            shuffle_records=shuffle_records, repeat=repeat)
    elif source_format == "jsonl":
        source = JsonlSource(
            files, seed=seed, process_index=process_index,
            process_count=process_count, shuffle_shards=shuffle_shards,
            shuffle_records=shuffle_records, repeat=repeat)
    else:
        raise ValueError(f"unknown source_format {source_format!r} "
                         "(expected 'bin' or 'jsonl')")
    packer = SequencePacker(source, batch_size, seq_len, pad_id=pad_id,
                            split_long_docs=split_long_docs)
    feeder = None
    if device_feed:
        sharding = batch_sharding(mesh, batch_axes) if mesh is not None else None
        feeder = GlobalBatchFeeder(packer, sharding=sharding,
                                   prefetch_depth=prefetch_depth)
    return DataPipeline(source, packer, feeder)
