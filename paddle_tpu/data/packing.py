"""Greedy sequence packing: variable-length documents -> fixed [B, S].

XLA compiles one executable per shape, so the training step must see the
SAME [B, S] int32 batch every step. The packer turns the source's ragged
document stream into static-shape buffers:

    tokens      [B, S] int32 — documents back to back, 0-padded tails
    segment_ids [B, S] int32 — 1-based per-document id within a row,
                               0 on padding (the attention-mask /
                               loss-mask carrier for packed attention)
    positions   [B, S] int32 — position WITHIN each document (reset to 0
                               at every document boundary)

Packing is greedy and sequential — documents fill the current row until
one doesn't fit, then the row is closed (tail padded) and the next row
starts. A document longer than S is truncated (default) or split into
S-sized continuation segments (``split_long_docs=True``, token-lossless).
Deterministic by construction: output is a pure function of the source
stream, so the checkpointable state is only the in-flight carry —
``{"carry": [...tokens...]}`` (the document pulled from the source that
did not fit the emitted batch). Source position + packer carry together
resume the exact batch sequence.

Efficiency is tracked per batch (non-pad fraction of B*S) and exposed
both as rolling attributes (``efficiency``, ``batches``, ``docs_packed``,
``docs_truncated``) and as flag-gated ``data.*`` metrics.

numpy/stdlib-only at import (metrics import is lazy and no-ops without
paddle_tpu) so standalone tooling can drive it.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

from .protocol import CheckpointableIterator

_STATE_VERSION = 1


def _metrics():
    try:
        from ..observability import metrics as m

        return m if m.enabled() else None
    except Exception:
        return None


class SequencePacker(CheckpointableIterator):
    """Pack a document stream (iterator of 1-D int token arrays) into
    fixed-shape ``{"tokens", "segment_ids", "positions"}`` batches.

    ``drop_remainder=True`` (default) only emits full [B, S] batches — a
    partially-fillable final batch (finite source) is dropped, keeping
    every emitted shape static for XLA. With ``repeat=True`` sources the
    stream is infinite and nothing is ever dropped.
    """

    def __init__(self, source: Iterator, batch_size: int, seq_len: int,
                 pad_id: int = 0, split_long_docs: bool = False,
                 drop_remainder: bool = True):
        self.source = source
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        self.pad_id = int(pad_id)
        self.split_long_docs = bool(split_long_docs)
        self.drop_remainder = bool(drop_remainder)
        if self.batch_size < 1 or self.seq_len < 1:
            raise ValueError("batch_size and seq_len must be >= 1")
        self._carry: Optional[np.ndarray] = None  # doc that missed the batch
        # rolling packing stats
        self.batches = 0
        self.tokens_packed = 0      # non-pad tokens emitted
        self.docs_packed = 0
        self.docs_truncated = 0
        self.tokens_truncated = 0

    # ---------------- stats ----------------
    @property
    def efficiency(self) -> float:
        """Rolling non-pad fraction over every batch emitted so far."""
        cap = self.batches * self.batch_size * self.seq_len
        return self.tokens_packed / cap if cap else 0.0

    # ---------------- iteration ----------------
    def _next_doc(self) -> Optional[np.ndarray]:
        if self._carry is not None:
            doc, self._carry = self._carry, None
            return doc
        while True:
            try:
                doc = next(self.source)
            except StopIteration:
                return None
            doc = np.asarray(doc, dtype=np.int32).reshape(-1)
            if doc.size:
                return doc

    def __next__(self) -> Dict[str, np.ndarray]:
        B, S = self.batch_size, self.seq_len
        tokens = np.full((B, S), self.pad_id, dtype=np.int32)
        segments = np.zeros((B, S), dtype=np.int32)
        positions = np.zeros((B, S), dtype=np.int32)
        row, col, seg, placed = 0, 0, 0, 0
        docs0, trunc0 = self.docs_packed, self.docs_truncated
        while row < B:
            doc = self._next_doc()
            if doc is None:  # source exhausted
                if placed == 0 or self.drop_remainder:
                    raise StopIteration
                break
            n = doc.size
            if n > S:
                if self.split_long_docs:
                    # the first S-col tokens continue below; the rest is
                    # carried as a fresh document (lossless)
                    n = S - col if col else S
                else:
                    self.docs_truncated += 1
                    self.tokens_truncated += doc.size - S
                    doc, n = doc[:S], S
            if n > S - col:  # close this row, retry the doc on the next
                self._carry = doc
                row += 1
                col = 0
                seg = 0
                continue
            if self.split_long_docs and doc.size > n:
                self._carry = doc[n:]
                doc = doc[:n]
            tokens[row, col:col + n] = doc
            segments[row, col:col + n] = seg + 1
            positions[row, col:col + n] = np.arange(n, dtype=np.int32)
            col += n
            seg += 1
            placed += n
            self.docs_packed += 1
            if col == S:
                row += 1
                col = 0
                seg = 0
        self.batches += 1
        self.tokens_packed += placed
        m = _metrics()
        if m is not None:
            m.counter("data.batches")
            m.counter("data.tokens", placed)
            m.gauge("data.packing.efficiency", placed / (B * S))
            m.counter("data.docs", self.docs_packed - docs0, event="packed")
            if self.docs_truncated > trunc0:
                m.counter("data.docs", self.docs_truncated - trunc0,
                          event="truncated")
        return {"tokens": tokens, "segment_ids": segments,
                "positions": positions}

    # ---------------- protocol ----------------
    def get_state(self) -> dict:
        return {
            "version": _STATE_VERSION,
            "carry": None if self._carry is None else
                     [int(t) for t in self._carry],
        }

    def set_state(self, state: dict) -> None:
        carry = state.get("carry")
        self._carry = (None if carry is None
                       else np.asarray(carry, dtype=np.int32))
