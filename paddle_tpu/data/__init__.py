"""paddle_tpu.data — deterministic sharded input pipeline (TPU-native
rebuild of the fleet InMemoryDataset/QueueDataset capability + the LLM
pretraining input path the reference kept in external tooling).

Stages, each a checkpointable iterator (``get_state``/``set_state``):

  sources   TokenBinSource / JsonlSource / TextLineSource — per-host
            file-shard readers with epoch-seeded deterministic shuffling
  packing   SequencePacker — greedy pack of ragged documents into static
            [B, S] token/segment-id/position buffers (XLA needs one shape)
  feed      GlobalBatchFeeder — per-host batches assembled into ONE
            mesh-global jax.Array over the data axis, double-buffered
            through io.prefetch.DevicePrefetcher
  pipeline  DataPipeline / build_pretrain_pipeline — composition whose
            single state dict plugs into TrainState.data_position for
            exact mid-epoch resume

See data/README.md for the contracts and tools/data_inspect.py for
offline shard/assignment/packing inspection (no jax required).
"""

from .protocol import (  # noqa: F401
    CheckpointableIterator,
    iterator_state,
    mix_seed,
    restore_iterator,
)
from .sources import (  # noqa: F401
    CoverageError,
    JsonlSource,
    ShardedFileSource,
    TextLineSource,
    TokenBinSource,
    expand_files,
    shard_assignment,
    validate_coverage,
)
from .packing import SequencePacker  # noqa: F401
from .feed import GlobalBatchFeeder, batch_sharding  # noqa: F401
from .pipeline import DataPipeline, build_pretrain_pipeline  # noqa: F401

__all__ = [
    "CheckpointableIterator", "iterator_state", "restore_iterator",
    "mix_seed",
    "ShardedFileSource", "TokenBinSource", "JsonlSource", "TextLineSource",
    "expand_files", "shard_assignment", "validate_coverage", "CoverageError",
    "SequencePacker",
    "GlobalBatchFeeder", "batch_sharding",
    "DataPipeline", "build_pretrain_pipeline",
]
