"""File-shard sources: deterministic, per-host, checkpointable readers.

The GSPMD input contract (arxiv 2105.04663) is that each host reads a
DISJOINT slice of the global data and the mesh assembles the global batch
from those per-process slices. These sources own the "disjoint + exactly
reproducible" half:

  * shard assignment — the sorted global file list is permuted with an
    epoch-seeded RNG and dealt round-robin by ``(process_index,
    process_count)``; every host computes the same permutation, so
    assignment is coordination-free and disjoint by construction;
  * epoch-seeded shuffling — shard order (and optionally document order
    inside a shard) reshuffles every epoch from ``mix_seed(seed, epoch)``,
    never from ambient RNG state, so epoch k's order is a pure function of
    (seed, k) and a resumed run replays it exactly;
  * checkpointable position — ``get_state()`` is (epoch, shard_cursor,
    intra-shard offset); ``set_state`` reproduces the identical remaining
    record stream.

The module is numpy/stdlib-only: no jax import at module load, so
``tools/data_inspect.py`` can drive it standalone. The process identity
defaults lazily to ``jax.process_index()/process_count()`` only when jax
is importable, else (0, 1).

Reference surface being rebuilt: fleet's InMemoryDataset/QueueDataset
file-list ingestion (distributed/fleet/dataset/dataset.py) — see
``distributed/fleet_dataset.py``, now re-backed by ``TextLineSource``.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from .protocol import CheckpointableIterator, mix_seed

_STATE_VERSION = 1


def _default_process() -> tuple:
    """(process_index, process_count), lazily from jax; (0, 1) without it."""
    try:
        import jax

        return jax.process_index(), max(jax.process_count(), 1)
    except Exception:
        return 0, 1


def expand_files(files, sort: bool = True) -> List[str]:
    """str glob / list of paths-or-globs -> deduped file list, sorted by
    default. Sorting is load-bearing for multi-host use: every host must
    derive the same global order from the same pattern. ``sort=False``
    keeps the caller's explicit order (the fleet set_filelist contract,
    where the list itself IS the agreed order)."""
    if isinstance(files, (str, os.PathLike)):
        files = [files]
    out: List[str] = []
    for f in files:
        f = os.fspath(f)
        matches = sorted(_glob.glob(f)) if _glob.has_magic(f) else [f]
        out.extend(matches)
    seen, uniq = set(), []
    for f in out:
        if f not in seen:
            seen.add(f)
            uniq.append(f)
    return sorted(uniq) if sort else uniq


def shard_assignment(files: Sequence[str], process_index: int,
                     process_count: int, seed: int = 0, epoch: int = 0,
                     shuffle: bool = True) -> List[str]:
    """This host's shard list for one epoch. Pure function of its inputs —
    the whole-fleet property (disjoint, covering, deterministic) follows
    from every host permuting the same sorted list with the same seed and
    taking a strided slice."""
    files = list(files)
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} out of range for "
            f"process_count {process_count}")
    if shuffle:
        order = np.random.RandomState(mix_seed(seed, epoch)).permutation(len(files))
    else:
        order = np.arange(len(files))
    return [files[i] for i in order[process_index::process_count]]


class CoverageError(ValueError):
    """The fleet's shard assignment is not a partition of the file list
    (a shard unowned, or owned twice)."""


def validate_coverage(files: Sequence[str], process_count: int,
                      seed: int = 0, epoch: int = 0,
                      shuffle: bool = True) -> dict:
    """Prove the whole-fleet property for one epoch at one world size:
    every file owned by EXACTLY one process. Cheap (pure python over the
    file list), so the elastic runner re-runs it after every re-assignment
    rather than trusting the construction. Returns {file: owner}."""
    owners: dict = {}
    dups = {}
    for pi in range(int(process_count)):
        for f in shard_assignment(files, pi, process_count, seed=seed,
                                  epoch=epoch, shuffle=shuffle):
            if f in owners:
                dups.setdefault(f, [owners[f]]).append(pi)
            else:
                owners[f] = pi
    missing = [f for f in files if f not in owners]
    if missing or dups:
        raise CoverageError(
            f"shard assignment at process_count={process_count} epoch="
            f"{epoch} is not a partition: {len(missing)} unowned file(s) "
            f"{missing[:3]}..., {len(dups)} multiply-owned {dict(list(dups.items())[:3])}")
    return owners


class ShardedFileSource(CheckpointableIterator):
    """Base class: epoch/shard/offset bookkeeping over per-host file shards.

    Subclasses implement ``_read_shard(path) -> list_of_records`` (the
    record index for one shard; records are yielded in list order, after
    the optional epoch-seeded intra-shard permutation).

    State: ``{"epoch", "shard_cursor", "offset"}`` — offset counts records
    already YIELDED from the current shard, so restore skips exactly that
    many and the remaining stream is identical.
    """

    def __init__(self, files, process_index: Optional[int] = None,
                 process_count: Optional[int] = None, seed: int = 0,
                 shuffle_shards: bool = True, shuffle_records: bool = False,
                 repeat: bool = True, sort_files: bool = True):
        self.files = expand_files(files, sort=sort_files)
        if not self.files:
            raise FileNotFoundError(f"no shard files match {files!r}")
        if process_index is None or process_count is None:
            # only consult jax when the caller didn't pin the identity —
            # keeps explicit-identity use (tools, tests) jax-free
            dflt = _default_process()
            process_index = dflt[0] if process_index is None else process_index
            process_count = dflt[1] if process_count is None else process_count
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        if len(self.files) < self.process_count:
            raise ValueError(
                f"{len(self.files)} shard file(s) cannot feed "
                f"{self.process_count} processes disjointly — write at least "
                "one shard per host")
        self.seed = int(seed)
        self.shuffle_shards = bool(shuffle_shards)
        self.shuffle_records = bool(shuffle_records)
        self.repeat = bool(repeat)
        self._epoch = 0
        self._shard_cursor = 0   # index into this epoch's local shard order
        self._offset = 0         # records yielded from the current shard
        self._records: Optional[list] = None  # current shard's record index
        self._exhausted = False
        self._empty_epochs = 0  # consecutive rollovers with no records
        # elastic residue (reassign mid-epoch): shards already consumed this
        # epoch under the OLD identity, and partial offsets to resume at
        self._epoch_done: set = set()
        self._partial_resume: dict = {}

    # ---------------- subclass surface ----------------
    def _read_shard(self, path: str) -> list:
        raise NotImplementedError

    # ---------------- assignment ----------------
    def local_shards(self, epoch: Optional[int] = None) -> List[str]:
        return shard_assignment(
            self.files, self.process_index, self.process_count,
            seed=self.seed, epoch=self._epoch if epoch is None else epoch,
            shuffle=self.shuffle_shards)

    @property
    def epoch(self) -> int:
        return self._epoch

    # ---------------- iteration ----------------
    def _record_order(self, n: int, path: str) -> np.ndarray:
        if self.shuffle_records:
            # salted by the shard's GLOBAL index, not the local cursor: the
            # intra-shard order must be a property of the shard itself so a
            # partially-read shard adopted by another host (elastic
            # reassign) resumes the same sequence
            return np.random.RandomState(
                mix_seed(self.seed, self._epoch, self.files.index(path), 1)
            ).permutation(n)
        return np.arange(n)

    def _load_current_shard(self) -> bool:
        """Position _records on the cursor's shard; False when the epoch is
        done (cursor past the local list). Shards another identity already
        consumed this epoch are skipped; partially-consumed ones resume at
        their recorded offset."""
        shards = self.local_shards()
        while self._shard_cursor < len(shards):
            path = shards[self._shard_cursor]
            if path in self._epoch_done:
                self._shard_cursor += 1
                self._offset = 0
                continue
            if self._offset == 0 and path in self._partial_resume:
                self._offset = int(self._partial_resume.pop(path))
            recs = self._read_shard(path)
            order = self._record_order(len(recs), path)
            recs = [recs[i] for i in order]
            if self._offset < len(recs):
                self._records = recs[self._offset:]
                return True
            # offset can only exceed the shard via a stale restore; treat
            # as shard-consumed and move on
            self._shard_cursor += 1
            self._offset = 0
        return False

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        while True:
            if self._records:
                self._offset += 1
                self._empty_epochs = 0
                return self._records.pop(0)
            if self._records is not None:  # current shard drained
                self._shard_cursor += 1
                self._offset = 0
                self._records = None
            if not self._load_current_shard():
                self._empty_epochs += 1
                if self.repeat and self._empty_epochs >= 2:
                    # two consecutive full scans found nothing: the local
                    # shard set is empty, repeat=True would spin forever
                    raise RuntimeError(
                        f"shard files for process {self.process_index} hold "
                        "no records")
                self._epoch += 1
                self._shard_cursor = 0
                self._offset = 0
                self._records = None
                self._epoch_done.clear()       # elastic residue is per-epoch
                self._partial_resume.clear()
                if not self.repeat:
                    self._exhausted = True
                    raise StopIteration

    # ---------------- elastic re-assignment ----------------
    def shard_progress(self) -> dict:
        """This identity's consumption of the CURRENT epoch: shards fully
        read (``done``) and in-flight offsets (``partial``) — the unit a
        surviving host hands to ``reassign`` so a dead peer's work isn't
        replayed and a partial shard resumes instead of restarting."""
        shards = self.local_shards()
        done = set(self._epoch_done)
        done.update(shards[:self._shard_cursor])
        partial = {p: int(o) for p, o in self._partial_resume.items()}
        if self._shard_cursor < len(shards) and self._offset > 0:
            partial[shards[self._shard_cursor]] = self._offset
        partial = {p: o for p, o in partial.items() if p not in done}
        return {"epoch": self._epoch, "done": sorted(done),
                "partial": partial}

    def reassign(self, process_index: int, process_count: int,
                 peer_progress=None, validate: bool = True
                 ) -> "ShardedFileSource":
        """Adopt a new fleet identity mid-epoch (elastic shrink/grow).

        Re-deals the file list at the new ``(process_index,
        process_count)`` and folds in epoch progress — this source's own
        plus any ``peer_progress`` (``shard_progress()`` dicts from OTHER
        former identities, e.g. recovered from a dead host's checkpoint) —
        so already-consumed shards are skipped and cursor-carrying shards
        resume at their offset rather than restarting. With ``validate``
        (default) the new assignment is proven to be a partition via
        ``validate_coverage`` before the switch. Calling ``set_state``
        across a world-size change instead of this raises (see there):
        that path silently skips/double-reads shards."""
        process_index, process_count = int(process_index), int(process_count)
        if not 0 <= process_index < process_count:
            raise ValueError(
                f"process_index {process_index} out of range for "
                f"process_count {process_count}")
        if len(self.files) < process_count:
            raise ValueError(
                f"{len(self.files)} shard file(s) cannot feed "
                f"{process_count} processes disjointly")
        progress = [self.shard_progress()]
        for p in (peer_progress or []):
            if int(p.get("epoch", -1)) == self._epoch:
                progress.append(p)  # stale-epoch peer state is meaningless
        if validate:
            validate_coverage(self.files, process_count, seed=self.seed,
                              epoch=self._epoch, shuffle=self.shuffle_shards)
        done: set = set()
        partial: dict = {}
        for p in progress:
            done.update(p.get("done") or [])
            for path, off in (p.get("partial") or {}).items():
                partial[path] = max(int(off), partial.get(path, 0))
        self.process_index = process_index
        self.process_count = process_count
        self._epoch_done = done
        self._partial_resume = {p: o for p, o in partial.items()
                                if p not in done and o > 0}
        self._shard_cursor = 0
        self._offset = 0
        self._records = None
        self._exhausted = False
        return self

    # ---------------- protocol ----------------
    def get_state(self) -> dict:
        state = {
            "version": _STATE_VERSION,
            "epoch": self._epoch,
            "shard_cursor": self._shard_cursor,
            "offset": self._offset,
            "process_index": self.process_index,
            "process_count": self.process_count,
        }
        if self._epoch_done:
            state["done_shards"] = sorted(self._epoch_done)
        if self._partial_resume:
            state["partial_shards"] = dict(self._partial_resume)
        return state

    def set_state(self, state: dict) -> None:
        pc = state.get("process_count")
        if pc is not None and int(pc) != self.process_count:
            raise ValueError(
                f"state was written at process_count {pc} but this source "
                f"runs at {self.process_count} — a blind restore would "
                "skip or double-read shards; use reassign() for elastic "
                "world-size changes")
        self._epoch = int(state["epoch"])
        self._shard_cursor = int(state["shard_cursor"])
        self._offset = int(state["offset"])
        self._records = None
        self._exhausted = False
        self._epoch_done = set(state.get("done_shards") or [])
        self._partial_resume = {k: int(v) for k, v in
                                (state.get("partial_shards") or {}).items()}


class TokenBinSource(ShardedFileSource):
    """Token ``.bin`` shards -> one int32 numpy array per document.

    Each shard is a flat token dump (``np.memmap``-readable, ``dtype``
    tokens back to back). With ``eos_id`` set, documents are the spans
    ENDING at each eos token (the eos stays with its document — the
    megatron-style boundary); trailing tokens after the last eos form a
    final document. Without ``eos_id``, the shard splits into fixed
    ``chunk_len`` documents (last partial chunk kept).
    """

    def __init__(self, files, dtype="uint16", eos_id: Optional[int] = None,
                 chunk_len: Optional[int] = None, **kw):
        if eos_id is None and chunk_len is None:
            raise ValueError("TokenBinSource needs eos_id or chunk_len to "
                             "delimit documents")
        self.dtype = np.dtype(dtype)
        self.eos_id = eos_id
        self.chunk_len = chunk_len
        super().__init__(files, **kw)

    def _read_shard(self, path: str) -> list:
        if os.path.getsize(path) == 0:
            return []  # memmap rejects empty files; an empty shard is legal
        tokens = np.memmap(path, dtype=self.dtype, mode="r")
        if self.eos_id is not None:
            ends = np.flatnonzero(tokens == self.dtype.type(self.eos_id)) + 1
            if len(ends) == 0 or ends[-1] != len(tokens):
                ends = np.append(ends, len(tokens))
            starts = np.concatenate(([0], ends[:-1]))
        else:
            starts = np.arange(0, len(tokens), self.chunk_len)
            ends = np.minimum(starts + self.chunk_len, len(tokens))
        return [np.asarray(tokens[s:e], dtype=np.int32)
                for s, e in zip(starts, ends) if e > s]


class JsonlSource(ShardedFileSource):
    """``.jsonl`` shards -> one int32 token array per line.

    Lines with a ``tokens`` field use it directly; lines with only
    ``text`` go through ``tokenizer(text) -> list[int]`` when supplied,
    else a UTF-8 byte fallback (vocab 256) so the source works without any
    tokenizer dependency.
    """

    def __init__(self, files, tokens_field: str = "tokens",
                 text_field: str = "text",
                 tokenizer: Optional[Callable] = None, **kw):
        self.tokens_field = tokens_field
        self.text_field = text_field
        self.tokenizer = tokenizer
        super().__init__(files, **kw)

    def _tokens_of(self, obj) -> np.ndarray:
        if self.tokens_field in obj:
            return np.asarray(obj[self.tokens_field], dtype=np.int32)
        text = obj[self.text_field]
        if self.tokenizer is not None:
            return np.asarray(self.tokenizer(text), dtype=np.int32)
        return np.frombuffer(text.encode("utf-8"), dtype=np.uint8).astype(np.int32)

    def _read_shard(self, path: str) -> list:
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(self._tokens_of(json.loads(line)))
        return out


class TextLineSource(ShardedFileSource):
    """Plain-text shards -> one stripped, non-empty line (str) per record.
    The fleet InMemoryDataset/QueueDataset ingestion backbone."""

    def _read_shard(self, path: str) -> list:
        with open(path) as f:
            return [ln.strip() for ln in f if ln.strip()]
