"""paddle.incubate.nn analog: fused transformer blocks built on the Pallas
seams (fused_attention / fused_feedforward op analogs, SURVEY §2.2)."""

from .fused_transformer import FusedFeedForward, FusedMultiHeadAttention, FusedTransformerEncoderLayer  # noqa: F401
