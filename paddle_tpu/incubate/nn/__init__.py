"""paddle.incubate.nn analog: fused transformer blocks built on the Pallas
seams (fused_attention / fused_feedforward op analogs, SURVEY §2.2)."""

from . import functional  # noqa: F401
from .fused_transformer import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm,
    FusedDropoutAdd,
    FusedEcMoe,
    FusedFeedForward,
    FusedLinear,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)
