"""Fused transformer layers (fluid/operators/fused/fused_attention_op.cu,
fused_feedforward analogs). "Fused" on TPU means: route through the flash
attention Pallas kernel + let XLA fuse the elementwise chain; the API carries
the reference's pre/post-LN contract."""

from __future__ import annotations

from ... import nn
from ...nn import functional as F
from ...nn.layer.layers import Layer


class FusedMultiHeadAttention(Layer):
    def __init__(
        self,
        embed_dim: int,
        num_heads: int,
        dropout_rate: float = 0.0,
        attn_dropout_rate: float = 0.0,
        normalize_before: bool = False,
        need_weights: bool = False,
        qkv_weight_attr=None,
        epsilon: float = 1e-5,
        name=None,
    ):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(f"num_heads ({num_heads}) must evenly divide embed_dim ({embed_dim})")
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.attn_dropout_rate = attn_dropout_rate
        self.qkv = nn.Linear(embed_dim, 3 * embed_dim)
        self.proj = nn.Linear(embed_dim, embed_dim)
        self.ln = nn.LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = nn.Dropout(dropout_rate)

    def forward(self, x, attn_mask=None):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        B, S = x.shape[0], x.shape[1]
        qkv = self.qkv(x).reshape([B, S, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate, training=self.training
        )
        out = self.dropout(self.proj(out.reshape([B, S, self.embed_dim])))
        out = residual + out
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(
        self,
        d_model: int,
        dim_feedforward: int,
        dropout_rate: float = 0.1,
        activation: str = "relu",
        act_dropout_rate=None,
        epsilon: float = 1e-5,
        normalize_before: bool = False,
        name=None,
    ):
        super().__init__()
        self.fc1 = nn.Linear(d_model, dim_feedforward)
        self.fc2 = nn.Linear(dim_feedforward, d_model)
        self.ln = nn.LayerNorm(d_model, epsilon=epsilon)
        self.dropout = nn.Dropout(dropout_rate)
        self.act_dropout = nn.Dropout(dropout_rate if act_dropout_rate is None else act_dropout_rate)
        self.act = getattr(F, activation)
        self.normalize_before = normalize_before

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        out = self.fc2(self.act_dropout(self.act(self.fc1(x))))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(
        self,
        d_model: int,
        nhead: int,
        dim_feedforward: int,
        dropout_rate: float = 0.1,
        activation: str = "relu",
        attn_dropout_rate=None,
        act_dropout_rate=None,
        normalize_before: bool = False,
        name=None,
    ):
        super().__init__()
        self.attn = FusedMultiHeadAttention(
            d_model,
            nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before,
        )
        self.ffn = FusedFeedForward(
            d_model,
            dim_feedforward,
            dropout_rate=dropout_rate,
            activation=activation,
            act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
        )

    def forward(self, src, src_mask=None):
        return self.ffn(self.attn(src, attn_mask=src_mask))
