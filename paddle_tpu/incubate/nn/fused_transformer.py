"""Fused transformer layers (fluid/operators/fused/fused_attention_op.cu,
fused_feedforward analogs). "Fused" on TPU means: route through the flash
attention Pallas kernel + let XLA fuse the elementwise chain; the API carries
the reference's pre/post-LN contract."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...nn.layer.layers import Layer


class FusedMultiHeadAttention(Layer):
    def __init__(
        self,
        embed_dim: int,
        num_heads: int,
        dropout_rate: float = 0.0,
        attn_dropout_rate: float = 0.0,
        normalize_before: bool = False,
        need_weights: bool = False,
        qkv_weight_attr=None,
        epsilon: float = 1e-5,
        name=None,
    ):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(f"num_heads ({num_heads}) must evenly divide embed_dim ({embed_dim})")
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.attn_dropout_rate = attn_dropout_rate
        self.qkv = nn.Linear(embed_dim, 3 * embed_dim)
        self.proj = nn.Linear(embed_dim, embed_dim)
        self.ln = nn.LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = nn.Dropout(dropout_rate)

    def forward(self, x, attn_mask=None):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        B, S = x.shape[0], x.shape[1]
        qkv = self.qkv(x).reshape([B, S, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate, training=self.training
        )
        out = self.dropout(self.proj(out.reshape([B, S, self.embed_dim])))
        out = residual + out
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(
        self,
        d_model: int,
        dim_feedforward: int,
        dropout_rate: float = 0.1,
        activation: str = "relu",
        act_dropout_rate=None,
        epsilon: float = 1e-5,
        normalize_before: bool = False,
        name=None,
    ):
        super().__init__()
        self.fc1 = nn.Linear(d_model, dim_feedforward)
        self.fc2 = nn.Linear(dim_feedforward, d_model)
        self.ln = nn.LayerNorm(d_model, epsilon=epsilon)
        self.dropout = nn.Dropout(dropout_rate)
        self.act_dropout = nn.Dropout(dropout_rate if act_dropout_rate is None else act_dropout_rate)
        self.act = getattr(F, activation)
        self.normalize_before = normalize_before

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        out = self.fc2(self.act_dropout(self.act(self.fc1(x))))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(
        self,
        d_model: int,
        nhead: int,
        dim_feedforward: int,
        dropout_rate: float = 0.1,
        activation: str = "relu",
        attn_dropout_rate=None,
        act_dropout_rate=None,
        normalize_before: bool = False,
        name=None,
    ):
        super().__init__()
        self.attn = FusedMultiHeadAttention(
            d_model,
            nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before,
        )
        self.ffn = FusedFeedForward(
            d_model,
            dim_feedforward,
            dropout_rate=dropout_rate,
            activation=activation,
            act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
        )

    def forward(self, src, src_mask=None):
        return self.ffn(self.attn(src, attn_mask=src_mask))


class FusedLinear(Layer):
    """Linear whose matmul+bias fuses into one dot (reference FusedLinear /
    fused_gemm_epilogue). On TPU, XLA fuses the epilogue already — the class
    exists so checkpoints and code port unchanged."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = [out_features, in_features] if transpose_weight else [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter([out_features], attr=None if bias_attr is True else bias_attr, is_bias=True)

    def forward(self, x):
        w = self.weight
        if self.transpose_weight:
            from ...ops.linalg import t as _t

            w = _t(w)
        return F.linear(x, w, self.bias)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """out = LayerNorm(residual + dropout(x + bias)) in one fused chain
    (reference FusedBiasDropoutResidualLayerNorm)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None, bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.dropout = nn.Dropout(dropout_rate)
        self.ln = nn.LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, x, residual):
        return self.ln(residual + self.dropout(x + self.linear_bias))


class FusedDropoutAdd(Layer):
    """out = dropout(x) + y (reference FusedDropoutAdd)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.dropout = nn.Dropout(p, mode=mode)

    def forward(self, x, y):
        return self.dropout(x) + y


class FusedEcMoe(Layer):
    """Expert-choice MoE layer (reference FusedEcMoe): gate scores route each
    token to top experts; expert FFNs run as one batched einsum over the
    expert dim (MXU-batched, the TPU-native layout)."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        self.num_experts = num_experts
        self.act_type = act_type
        self.gate = nn.Linear(hidden_size, num_experts)
        self.w1 = self.create_parameter([num_experts, hidden_size, inter_size])
        self.b1 = self.create_parameter([num_experts, 1, inter_size], is_bias=True)
        self.w2 = self.create_parameter([num_experts, inter_size, hidden_size])
        self.b2 = self.create_parameter([num_experts, 1, hidden_size], is_bias=True)

    def forward(self, x, gate_logits=None):
        from ...ops._dispatch import apply, as_tensor

        if gate_logits is None:
            gate_logits = self.gate(x)
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[self.act_type]
        num_experts = self.num_experts

        def f(xv, gv, w1, b1, w2, b2):
            B, S, H = xv.shape
            T = B * S
            probs = jax.nn.softmax(gv.reshape(T, num_experts), -1)  # [T, E]
            flat = xv.reshape(T, H)
            # expert-choice routing: each expert picks its top-capacity tokens
            # (Zhou et al.; the reference kernel's contract) — capacity 2T/E
            capacity = max(1, min(T, (2 * T) // num_experts))
            expert_scores = probs.T  # [E, T]
            top_p, top_idx = jax.lax.top_k(expert_scores, capacity)  # [E, C]
            chosen = flat[top_idx]  # [E, C, H] gathered per expert
            h = act(jnp.einsum("ech,ehi->eci", chosen, w1) + b1)
            out = jnp.einsum("eci,eih->ech", h, w2) + b2  # [E, C, H]
            # combine: scatter-add each expert's outputs back, weighted by prob
            weighted = out * top_p[..., None]
            mixed = jnp.zeros((T, H), xv.dtype)
            for e in range(num_experts):  # E is small and static; unrolled adds fuse
                mixed = mixed.at[top_idx[e]].add(weighted[e])
            return mixed.reshape(B, S, H)

        return apply(
            "fused_ec_moe", f, as_tensor(x), as_tensor(gate_logits),
            self.w1, self.b1, self.w2, self.b2,
        )


class FusedMultiTransformer(Layer):
    """Stacked pre-LN decoder layers in ONE op (reference
    incubate/nn/layer/fused_transformer.py:1021 FusedMultiTransformer, the
    inference fast path of fused_multi_transformer_op.cu).

    TPU re-design: all layers' weights live STACKED on a leading [L, ...]
    dim and the block chain runs as a lax.scan — one traced block regardless
    of depth (compile time O(1) in L), with XLA fusing the intra-block
    chain. KV caches are [L, B, H, S_max, D] pairs; ``time_step`` selects
    the single-token decode path (write K/V at the position, attend over the
    valid prefix) — the generation loop the CUDA kernel serves.
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1,
                 epsilon=1e-5, kv_num_heads=None, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(f"embed_dim {embed_dim} % num_heads {num_heads}")
        if not normalize_before:
            raise NotImplementedError("FusedMultiTransformer is pre-LN only "
                                      "(reference normalize_before=True path)")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        # GQA serving: K/V carry kv_num_heads (< num_heads) — the KV cache
        # shrinks by num_heads/kv_num_heads, the binding memory at long S
        self.kv_num_heads = kv_num_heads if kv_num_heads is not None else num_heads
        if num_heads % self.kv_num_heads:
            raise ValueError(
                f"num_heads {num_heads} % kv_num_heads {self.kv_num_heads}")
        self.dim_feedforward = dim_feedforward
        self.num_layers = num_layers
        self.epsilon = epsilon
        self._act = activation
        L, H, F_ = num_layers, embed_dim, dim_feedforward
        qkv_out = (num_heads + 2 * self.kv_num_heads) * self.head_dim
        mk = self.create_parameter
        from ...nn import initializer as I

        ones, zeros = I.Constant(1.0), I.Constant(0.0)
        self.ln1_w = mk([L, H], default_initializer=ones)
        self.ln1_b = mk([L, H], default_initializer=zeros, is_bias=True)
        self.qkv_w = mk([L, H, qkv_out])
        self.qkv_b = mk([L, qkv_out], default_initializer=zeros, is_bias=True)
        self.proj_w = mk([L, H, H])
        self.proj_b = mk([L, H], default_initializer=zeros, is_bias=True)
        self.ln2_w = mk([L, H], default_initializer=ones)
        self.ln2_b = mk([L, H], default_initializer=zeros, is_bias=True)
        self.ffn1_w = mk([L, H, F_])
        self.ffn1_b = mk([L, F_], default_initializer=zeros, is_bias=True)
        self.ffn2_w = mk([L, F_, H])
        self.ffn2_b = mk([L, H], default_initializer=zeros, is_bias=True)

    def gen_cache(self, batch_size: int, max_seq_len: int, dtype="float32"):
        """Empty [L, B, heads, S_max, D] K and V caches (reference
        gen_cache contract for the generation loop)."""
        import jax.numpy as jnp

        from ...core.tensor import Tensor

        shape = (self.num_layers, batch_size, self.kv_num_heads, max_seq_len, self.head_dim)
        return Tensor(jnp.zeros(shape, dtype)), Tensor(jnp.zeros(shape, dtype))

    def forward(self, x, attn_mask=None, caches=None, time_step=None):
        """Prefill: x [B, S, H] -> [B, S, H] (causal); filling caches when
        given. Decode: x [B, 1, H] + time_step -> one-token output with the
        caches advanced. Returns (out, (k_cache, v_cache)) when caches are
        passed, else out — the reference's cache_kvs contract."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ...ops._dispatch import apply, as_tensor
        from ...serving import kv_cache as _kvc

        if attn_mask is not None:
            raise NotImplementedError(
                "FusedMultiTransformer is causal-only (the generation fast "
                "path); custom attn_mask is unsupported")
        x = as_tensor(x)
        nh, hd, eps, act_name = self.num_heads, self.head_dim, self.epsilon, self._act
        nkv = self.kv_num_heads
        rep = nh // nkv  # query heads per shared K/V head (1 = MHA)

        def ln(v, w, b):
            mu = v.mean(-1, keepdims=True)
            var = ((v - mu) ** 2).mean(-1, keepdims=True)
            return (v - mu) * jax.lax.rsqrt(var + eps) * w + b

        def act(v):
            return jax.nn.gelu(v, approximate=False) if act_name == "gelu" else jax.nn.relu(v)

        def block(h, p, k_layer, v_layer, step):
            """One decoder block on [B, T, H]; k_layer/v_layer are this
            layer's cache slices or None."""
            (l1w, l1b, qkvw, qkvb, pw, pb, l2w, l2b, f1w, f1b, f2w, f2b) = p
            B, T = h.shape[0], h.shape[1]
            z = ln(h, l1w, l1b)
            qkv = z @ qkvw + qkvb  # [B, T, (nh + 2*nkv)*hd]
            q, k, v = jnp.split(qkv, [nh * hd, (nh + nkv) * hd], axis=-1)
            q = q.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)  # [B, nh, T, hd]
            k = k.reshape(B, T, nkv, hd).transpose(0, 2, 1, 3)  # [B, nkv, T, hd]
            v = v.reshape(B, T, nkv, hd).transpose(0, 2, 1, 3)
            # caches store nkv heads (the GQA memory win); queries see the
            # shared heads via a broadcast XLA keeps fused into the einsum
            expand = (lambda t: jnp.repeat(t, rep, axis=1)) if rep > 1 else (lambda t: t)
            if k_layer is not None:
                if step is not None:
                    # decode: shared static-cache write/attend
                    # (serving.kv_cache) — the same path the GPT serving
                    # engine runs, so the two cached decode implementations
                    # cannot drift
                    k_layer = _kvc.write_kv(k_layer, k, step)
                    v_layer = _kvc.write_kv(v_layer, v, step)
                    o = _kvc.decode_attend(q, k_layer, v_layer, step)
                else:
                    # prefill: causal attention; caches filled with the prefix
                    k_layer = lax.dynamic_update_slice(k_layer, k, (0, 0, 0, 0))
                    v_layer = lax.dynamic_update_slice(v_layer, v, (0, 0, 0, 0))
                    o = _causal_attn(q, expand(k), expand(v))
            else:
                o = _causal_attn(q, expand(k), expand(v))
            o = o.transpose(0, 2, 1, 3).reshape(B, T, nh * hd)
            h = h + (o @ pw + pb)
            z = ln(h, l2w, l2b)
            h = h + (act(z @ f1w + f1b) @ f2w + f2b)
            return h, k_layer, v_layer

        def _causal_attn(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32) / jnp.sqrt(float(hd)).astype(jnp.float32)
            T = q.shape[2]
            s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None, None], s, -1e30)
            return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1).astype(v.dtype), v)

        params = (self.ln1_w, self.ln1_b, self.qkv_w, self.qkv_b,
                  self.proj_w, self.proj_b, self.ln2_w, self.ln2_b,
                  self.ffn1_w, self.ffn1_b, self.ffn2_w, self.ffn2_b)

        if caches is None:
            def fn(xv, *pv):
                def body(h, layer_p):
                    h2, _, _ = block(h, layer_p, None, None, None)
                    return h2, None
                out, _ = lax.scan(body, xv, tuple(pv))
                return out

            return apply("fused_multi_transformer", fn, x, *params)

        k_cache, v_cache = caches
        k_cache, v_cache = as_tensor(k_cache), as_tensor(v_cache)
        step_t = as_tensor(time_step) if time_step is not None else None
        has_step = step_t is not None

        def fn(xv, kc, vc, *rest):
            if has_step:
                step = rest[0].astype(jnp.int32).reshape(())
                pv = rest[1:]
            else:
                step, pv = None, rest

            def body(h, layer_in):
                layer_p, kl, vl = layer_in[:-2], layer_in[-2], layer_in[-1]
                h2, kl2, vl2 = block(h, layer_p, kl, vl, step)
                return h2, (kl2, vl2)

            out, (nk, nv) = lax.scan(body, xv, tuple(pv) + (kc, vc))
            return out, nk, nv

        args = (x, k_cache, v_cache) + ((step_t,) if has_step else ()) + params
        out, nk, nv = apply("fused_multi_transformer_cached", fn, *args)
        return out, (nk, nv)
