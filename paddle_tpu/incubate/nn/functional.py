"""paddle.incubate.nn.functional (reference incubate/nn/functional/):
the fused-op functional surface. On TPU "fused" means one traced
expression XLA fuses — these exist so serving/training code written
against the reference's fused API ports unchanged.

fused_rotary_position_embedding re-designs the RoPE CUDA kernel
(fused_rotary_position_embedding.py) as pure jnp: build cos/sin once,
rotate q/k in one fused elementwise block on the VPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._dispatch import apply, as_tensor

__all__ = [
    "fused_dropout_add",
    "fused_linear",
    "fused_rms_norm",
    "fused_rotary_position_embedding",
]


def _rope_pair(x, cos, sin, use_neox: bool):
    """Rotate the feature pairs of x [B, S, H, D] by (cos, sin) [S, D]."""
    if use_neox:
        # neox style: rotate halves (x1 = x[..., :D/2], x2 = x[..., D/2:])
        D = x.shape[-1]
        x1, x2 = x[..., : D // 2], x[..., D // 2:]
        rot = jnp.concatenate([-x2, x1], axis=-1)
    else:
        # GPT-J style: rotate even/odd interleaved pairs
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        rot = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
    return x * cos[None, :, None, :] + rot * sin[None, :, None, :]


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major: bool = False, rotary_emb_base=10000.0):
    """RoPE over q/k[/v] [B, S, H, D] (reference
    incubate/nn/functional/fused_rotary_position_embedding.py). With
    sin/cos None they are built from rotary_emb_base; position_ids
    optionally gathers per-batch positions. Returns the same tuple arity
    it was given ((q,), (q, k) or (q, k, v))."""
    q = as_tensor(q)
    if time_major:
        # [S, B, H, D] layout: rotate in batch-major form and restore below
        def tm(t):
            return Tensor(jnp.swapaxes(as_tensor(t)._value, 0, 1))

        outs = fused_rotary_position_embedding(
            tm(q), tm(k) if k is not None else None,
            tm(v) if v is not None else None, sin=sin, cos=cos,
            position_ids=position_ids,
            use_neox_rotary_style=use_neox_rotary_style,
            time_major=False, rotary_emb_base=rotary_emb_base)
        outs = outs if isinstance(outs, tuple) else (outs,)
        back = tuple(Tensor(jnp.swapaxes(o._value, 0, 1)) for o in outs)
        return back if len(back) > 1 else back[0]
    B, S, H, D = q.shape

    if cos is None or sin is None:
        # with explicit position_ids the table must cover max(position)+1
        # rows, not just S (KV-cache decode gathers positions >= S)
        n_rows = S
        if position_ids is not None:
            pid_v = as_tensor(position_ids)._value
            if isinstance(pid_v, jax.core.Tracer):
                raise ValueError(
                    "fused_rotary_position_embedding: pass explicit sin/cos "
                    "tables when position_ids is traced (the generated "
                    "table's length can't depend on traced values)")
            n_rows = max(S, int(pid_v.max()) + 1)
        pos = jnp.arange(n_rows, dtype=jnp.float32)
        inv = rotary_emb_base ** (-jnp.arange(0, D, 2, dtype=jnp.float32) / D)
        freqs = pos[:, None] * inv[None, :]  # [n_rows, D/2]
        if use_neox_rotary_style:
            emb = jnp.concatenate([freqs, freqs], axis=-1)  # [n_rows, D]
        else:
            emb = jnp.repeat(freqs, 2, axis=-1)
        cos_v, sin_v = jnp.cos(emb), jnp.sin(emb)
    else:
        # full table; truncate to S only when gathering positionally 0..S-1
        cos_v = as_tensor(cos)._value.reshape(-1, D)
        sin_v = as_tensor(sin)._value.reshape(-1, D)
        if position_ids is None:
            cos_v, sin_v = cos_v[:S], sin_v[:S]

    if position_ids is not None:
        pid = as_tensor(position_ids)._value  # [B, S]
        cos_v = cos_v[pid]  # [B, S, D]
        sin_v = sin_v[pid]

    def rope_one(t):
        tv = t._value
        c, s = cos_v.astype(tv.dtype), sin_v.astype(tv.dtype)
        if position_ids is not None:
            if use_neox_rotary_style:
                Dh = tv.shape[-1]
                x1, x2 = tv[..., : Dh // 2], tv[..., Dh // 2:]
                rot = jnp.concatenate([-x2, x1], axis=-1)
            else:
                rot = jnp.stack([-tv[..., 1::2], tv[..., 0::2]], axis=-1).reshape(tv.shape)
            return Tensor(tv * c[:, :, None, :] + rot * s[:, :, None, :])
        return Tensor(_rope_pair(tv, c, s, use_neox_rotary_style))

    outs = [rope_one(q)]
    if k is not None:
        outs.append(rope_one(as_tensor(k)))
    if v is not None:
        outs.append(as_tensor(v))  # reference: v passes through un-rotated
    return tuple(outs) if len(outs) > 1 else outs[0]


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """dropout(x) + y in one fused expression (reference
    incubate/nn/functional/fused_dropout_add.py)."""
    from ...nn.functional import dropout

    x = as_tensor(x)
    y = as_tensor(y)
    return dropout(x, p=p, training=training, mode=mode) + y


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """One-matmul linear (reference incubate fused_linear / fused_gemm)."""
    x = as_tensor(x)
    w = as_tensor(weight)

    def f(xv, wv, *rest):
        wv2 = wv.T if transpose_weight else wv
        out = xv @ wv2
        return out + rest[0] if rest else out

    args = [x, w] + ([as_tensor(bias)] if bias is not None else [])
    return apply("fused_linear", f, *args)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, name=None):
    """RMSNorm through the fused kernel seam (reference fused_rms_norm).
    begin_norm_axis normalizes over ALL trailing axes from that index
    (the reference layer_norm-style contract)."""
    x = as_tensor(x)
    nd = len(x.shape)
    axis = begin_norm_axis % nd
    if axis == nd - 1:
        from ...nn.functional import rms_norm

        out = rms_norm(x, weight=norm_weight, epsilon=epsilon)
    else:
        w = as_tensor(norm_weight)

        def f(xv, wv):
            axes = tuple(range(axis, xv.ndim))
            ms = jnp.mean(jnp.square(xv.astype(jnp.float32)), axis=axes,
                          keepdims=True)
            out = xv.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)
            return (out * wv.reshape(xv.shape[axis:]).astype(jnp.float32)
                    ).astype(xv.dtype)

        out = apply("fused_rms_norm", f, x, w)
    if norm_bias is not None:
        out = out + as_tensor(norm_bias)
    return out
