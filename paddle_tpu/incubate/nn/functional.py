"""paddle.incubate.nn.functional (reference incubate/nn/functional/):
the fused-op functional surface. On TPU "fused" means one traced
expression XLA fuses — these exist so serving/training code written
against the reference's fused API ports unchanged.

fused_rotary_position_embedding re-designs the RoPE CUDA kernel
(fused_rotary_position_embedding.py) as pure jnp: build cos/sin once,
rotate q/k in one fused elementwise block on the VPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._dispatch import apply, as_tensor

__all__ = [
    "fused_bias_dropout_residual_layer_norm",
    "fused_dropout_add",
    "fused_ec_moe",
    "fused_feedforward",
    "fused_linear",
    "fused_matmul_bias",
    "fused_multi_head_attention",
    "fused_multi_transformer",
    "fused_rms_norm",
    "fused_rotary_position_embedding",
]


def _rope_pair(x, cos, sin, use_neox: bool):
    """Rotate the feature pairs of x [B, S, H, D] by (cos, sin) [S, D]."""
    if use_neox:
        # neox style: rotate halves (x1 = x[..., :D/2], x2 = x[..., D/2:])
        D = x.shape[-1]
        x1, x2 = x[..., : D // 2], x[..., D // 2:]
        rot = jnp.concatenate([-x2, x1], axis=-1)
    else:
        # GPT-J style: rotate even/odd interleaved pairs
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        rot = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
    return x * cos[None, :, None, :] + rot * sin[None, :, None, :]


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major: bool = False, rotary_emb_base=10000.0):
    """RoPE over q/k[/v] [B, S, H, D] (reference
    incubate/nn/functional/fused_rotary_position_embedding.py). With
    sin/cos None they are built from rotary_emb_base; position_ids
    optionally gathers per-batch positions. Returns the same tuple arity
    it was given ((q,), (q, k) or (q, k, v))."""
    q = as_tensor(q)
    if time_major:
        # [S, B, H, D] layout: rotate in batch-major form and restore below
        def tm(t):
            return Tensor(jnp.swapaxes(as_tensor(t)._value, 0, 1))

        outs = fused_rotary_position_embedding(
            tm(q), tm(k) if k is not None else None,
            tm(v) if v is not None else None, sin=sin, cos=cos,
            position_ids=position_ids,
            use_neox_rotary_style=use_neox_rotary_style,
            time_major=False, rotary_emb_base=rotary_emb_base)
        outs = outs if isinstance(outs, tuple) else (outs,)
        back = tuple(Tensor(jnp.swapaxes(o._value, 0, 1)) for o in outs)
        return back if len(back) > 1 else back[0]
    B, S, H, D = q.shape

    if cos is None or sin is None:
        # with explicit position_ids the table must cover max(position)+1
        # rows, not just S (KV-cache decode gathers positions >= S)
        n_rows = S
        if position_ids is not None:
            pid_v = as_tensor(position_ids)._value
            if isinstance(pid_v, jax.core.Tracer):
                raise ValueError(
                    "fused_rotary_position_embedding: pass explicit sin/cos "
                    "tables when position_ids is traced (the generated "
                    "table's length can't depend on traced values)")
            n_rows = max(S, int(pid_v.max()) + 1)
        pos = jnp.arange(n_rows, dtype=jnp.float32)
        inv = rotary_emb_base ** (-jnp.arange(0, D, 2, dtype=jnp.float32) / D)
        freqs = pos[:, None] * inv[None, :]  # [n_rows, D/2]
        if use_neox_rotary_style:
            emb = jnp.concatenate([freqs, freqs], axis=-1)  # [n_rows, D]
        else:
            emb = jnp.repeat(freqs, 2, axis=-1)
        cos_v, sin_v = jnp.cos(emb), jnp.sin(emb)
    else:
        # full table; truncate to S only when gathering positionally 0..S-1
        cos_v = as_tensor(cos)._value.reshape(-1, D)
        sin_v = as_tensor(sin)._value.reshape(-1, D)
        if position_ids is None:
            cos_v, sin_v = cos_v[:S], sin_v[:S]

    if position_ids is not None:
        pid = as_tensor(position_ids)._value  # [B, S]
        cos_v = cos_v[pid]  # [B, S, D]
        sin_v = sin_v[pid]

    def rope_one(t):
        tv = t._value
        c, s = cos_v.astype(tv.dtype), sin_v.astype(tv.dtype)
        if position_ids is not None:
            if use_neox_rotary_style:
                Dh = tv.shape[-1]
                x1, x2 = tv[..., : Dh // 2], tv[..., Dh // 2:]
                rot = jnp.concatenate([-x2, x1], axis=-1)
            else:
                rot = jnp.stack([-tv[..., 1::2], tv[..., 0::2]], axis=-1).reshape(tv.shape)
            return Tensor(tv * c[:, :, None, :] + rot * s[:, :, None, :])
        return Tensor(_rope_pair(tv, c, s, use_neox_rotary_style))

    outs = [rope_one(q)]
    if k is not None:
        outs.append(rope_one(as_tensor(k)))
    if v is not None:
        outs.append(as_tensor(v))  # reference: v passes through un-rotated
    return tuple(outs) if len(outs) > 1 else outs[0]


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """dropout(x) + y in one fused expression (reference
    incubate/nn/functional/fused_dropout_add.py)."""
    from ...nn.functional import dropout

    x = as_tensor(x)
    y = as_tensor(y)
    return dropout(x, p=p, training=training, mode=mode) + y


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """One-matmul linear (reference incubate fused_linear / fused_gemm)."""
    x = as_tensor(x)
    w = as_tensor(weight)

    def f(xv, wv, *rest):
        wv2 = wv.T if transpose_weight else wv
        out = xv @ wv2
        return out + rest[0] if rest else out

    args = [x, w] + ([as_tensor(bias)] if bias is not None else [])
    return apply("fused_linear", f, *args)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, name=None):
    """RMSNorm through the fused kernel seam (reference fused_rms_norm).
    begin_norm_axis normalizes over ALL trailing axes from that index
    (the reference layer_norm-style contract)."""
    x = as_tensor(x)
    nd = len(x.shape)
    axis = begin_norm_axis % nd
    if axis == nd - 1:
        from ...nn.functional import rms_norm

        out = rms_norm(x, weight=norm_weight, epsilon=epsilon)
    else:
        w = as_tensor(norm_weight)

        def f(xv, wv):
            axes = tuple(range(axis, xv.ndim))
            ms = jnp.mean(jnp.square(xv.astype(jnp.float32)), axis=axes,
                          keepdims=True)
            out = xv.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)
            return (out * wv.reshape(xv.shape[axis:]).astype(jnp.float32)
                    ).astype(xv.dtype)

        out = apply("fused_rms_norm", f, x, w)
    if norm_bias is not None:
        out = out + as_tensor(norm_bias)
    return out


# ---- fused transformer functional surface (reference incubate/nn/
# functional/fused_transformer.py + fused_matmul_bias.py + fused_ec_moe.py).
# "Fused" on TPU = one traced expression XLA fuses; these exist so code
# written against the reference's functional fused API ports unchanged. ----


def _dropout(v, rate, training, key=None, mode="upscale_in_train"):
    """Reference dropout semantics: upscale_in_train scales kept values by
    1/(1-rate) during training and is identity at inference;
    downscale_in_infer keeps raw values during training and scales the
    WHOLE tensor by (1-rate) at inference."""
    if rate <= 0.0:
        return v
    if not training:
        return v * (1.0 - rate) if mode == "downscale_in_infer" else v
    from ...core import random as _random

    key = key if key is not None else _random.next_key()
    keep = jax.random.bernoulli(key, 1.0 - rate, v.shape)
    kept = v / (1.0 - rate) if mode == "upscale_in_train" else v
    return jnp.where(keep, kept, jnp.zeros_like(v))


def _layer_norm(v, scale, bias, eps):
    vf = v.astype(jnp.float32)
    mean = vf.mean(axis=-1, keepdims=True)
    var = vf.var(axis=-1, keepdims=True)
    out = (vf - mean) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        out = out * scale.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(v.dtype)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """matmul + bias epilogue (reference fused_matmul_bias, cuBLASLt path);
    XLA fuses the bias add into the dot."""
    x, y = as_tensor(x), as_tensor(y)
    args = [x, y] + ([as_tensor(bias)] if bias is not None else [])

    def f(xv, yv, *rest):
        xv = jnp.swapaxes(xv, -1, -2) if transpose_x else xv
        yv = jnp.swapaxes(yv, -1, -2) if transpose_y else yv
        out = xv @ yv
        return out + rest[0] if rest else out

    return apply("fused_matmul_bias", f, *args)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """y = layer_norm(residual + dropout(bias + x)) — reference
    fused_transformer.py:274."""
    x, residual = as_tensor(x), as_tensor(residual)
    args = [x, residual]
    for t in (bias, ln_scale, ln_bias):
        if t is not None:
            args.append(as_tensor(t))
    has = [bias is not None, ln_scale is not None, ln_bias is not None]

    from ...core import random as _random

    key = (_random.next_key() if training and dropout_rate > 0.0 else None)

    def f(xv, rv, *rest):
        i = 0
        b = rest[i] if has[0] else None
        i += has[0]
        s = rest[i] if has[1] else None
        i += has[1]
        lb = rest[i] if has[2] else None
        h = xv + b if b is not None else xv
        h = rv + _dropout(h, dropout_rate, training, key, mode)
        return _layer_norm(h, s, lb, ln_epsilon)

    return apply("fused_bias_dropout_residual_ln", f, *args)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, add_residual=True,
                      name=None):
    """Transformer FFN block (reference fused_transformer.py:31):
    residual + dropout2(linear2(dropout1(act(linear1(maybe_ln(x))))))
    with pre- or post-LN placement."""
    from ...core import random as _random

    tensors = {"x": as_tensor(x), "w1": as_tensor(linear1_weight),
               "w2": as_tensor(linear2_weight)}
    opt = {"b1": linear1_bias, "b2": linear2_bias, "s1": ln1_scale,
           "lb1": ln1_bias, "s2": ln2_scale, "lb2": ln2_bias}
    names = [k for k, v in opt.items() if v is not None]
    args = list(tensors.values()) + [as_tensor(opt[k]) for k in names]
    acts = {"relu": jax.nn.relu,
            "gelu": lambda v: jax.nn.gelu(v, approximate=False)}
    act = acts[activation]
    k1 = _random.next_key() if training and dropout1_rate > 0 else None
    k2 = _random.next_key() if training and dropout2_rate > 0 else None

    def f(xv, w1, w2, *rest):
        o = dict(zip(names, rest))
        res = xv
        h = _layer_norm(xv, o.get("s1"), o.get("lb1"), ln1_epsilon) \
            if pre_layer_norm else xv
        h = h @ w1
        if "b1" in o:
            h = h + o["b1"]
        h = _dropout(act(h), dropout1_rate, training, k1, mode)
        h = h @ w2
        if "b2" in o:
            h = h + o["b2"]
        h = _dropout(h, dropout2_rate, training, k2, mode)
        h = res + h if add_residual else h
        if not pre_layer_norm:
            h = _layer_norm(h, o.get("s2"), o.get("lb2"), ln2_epsilon)
        return h

    return apply("fused_feedforward", f, *args)


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False, pre_ln_scale=None,
        pre_ln_bias=None, ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
        qkv_bias=None, linear_bias=None, cache_kv=None, attn_mask=None,
        dropout_rate=0.5, attn_dropout_rate=0.5, ln_epsilon=1e-5,
        training=True, mode="upscale_in_train", ring_id=-1, add_residual=True,
        num_heads=-1, transpose_qkv_wb=False, name=None):
    """Self-attention block (reference fused_transformer.py:464): fused
    QKV projection -> scaled dot-product attention (+additive mask) ->
    output linear -> dropout -> residual -> LN (pre- or post-placement).
    qkv_weight: [3, num_heads, head_dim, embed_dim] (or [embed, 3*embed]
    with transpose_qkv_wb=True and num_heads given)."""
    from ...core import random as _random

    if cache_kv is not None:
        raise NotImplementedError(
            "cached decode: use incubate.nn.FusedMultiHeadAttention / "
            "FusedMultiTransformer (gen_cache + time_step)")
    xt, qkvw, lw = as_tensor(x), as_tensor(qkv_weight), as_tensor(linear_weight)
    opt = {"pre_s": pre_ln_scale, "pre_b": pre_ln_bias, "s": ln_scale,
           "lb": ln_bias, "qb": qkv_bias, "ob": linear_bias,
           "mask": attn_mask}
    names = [k for k, v in opt.items() if v is not None]
    args = [xt, qkvw, lw] + [as_tensor(opt[k]) for k in names]
    ka = _random.next_key() if training and attn_dropout_rate > 0 else None
    kd = _random.next_key() if training and dropout_rate > 0 else None

    def f(xv, qw, lwv, *rest):
        o = dict(zip(names, rest))
        B, S, E = xv.shape
        res = xv
        h = _layer_norm(xv, o.get("pre_s"), o.get("pre_b"), pre_ln_epsilon) \
            if pre_layer_norm else xv
        if transpose_qkv_wb:
            if num_heads <= 0:
                raise ValueError(
                    "transpose_qkv_wb=True needs num_heads > 0 (the 2-D "
                    "qkv_weight carries no head structure)")
            H = num_heads
            qkv = h @ qw  # [B, S, 3E]
            if "qb" in o:
                qkv = qkv + o["qb"]
            qkv = qkv.reshape(B, S, 3, H, E // H)
        else:
            _, H, D, _ = qw.shape
            qkv = jnp.einsum("bse,thde->bsthd", h, qw)
            if "qb" in o:
                qkv = qkv + o["qb"][None, None]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B, S, H, D]
        D = q.shape[-1]
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) / jnp.sqrt(D)
        if "mask" in o:
            logits = logits + o["mask"].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = _dropout(probs, attn_dropout_rate, training, ka, mode)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
        out = out.reshape(B, S, -1) @ lwv
        if "ob" in o:
            out = out + o["ob"]
        out = _dropout(out, dropout_rate, training, kd, mode)
        out = res + out if add_residual else out
        if not pre_layer_norm:
            out = _layer_norm(out, o.get("s"), o.get("lb"), ln_epsilon)
        return out

    return apply("fused_multi_head_attention", f, *args)


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type):
    """Dense-gated expert mixture (reference fused_ec_moe): per token,
    out = sum_e softmax(gate)[..., e] * ffn_e(x)."""
    if act_type not in ("gelu", "relu"):
        raise ValueError(f"act_type must be gelu|relu, got {act_type!r}")
    args = [as_tensor(t) for t in
            (x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias)]
    act = jax.nn.relu if act_type == "relu" else \
        (lambda v: jax.nn.gelu(v, approximate=False))

    def f(xv, gv, w0, b0, w1, b1):
        probs = jax.nn.softmax(gv.astype(jnp.float32), axis=-1)
        h = jnp.einsum("bsd,edf->ebsf", xv, w0) + b0[:, None]
        h = act(h)
        y = jnp.einsum("ebsf,efd->ebsd", h, w1) + b1[:, None]
        return jnp.einsum("ebsd,bse->bsd", y,
                          probs.astype(y.dtype))

    return apply("fused_ec_moe", f, *args)


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, pre_caches=None, seq_lens=None,
        rotary_embs=None, time_step=None, attn_mask=None, dropout_rate=0.0,
        rotary_emb_dims=0, activation="gelu", training=False,
        mode="upscale_in_train", trans_qkvw=True, ring_id=-1, name=None):
    """Stacked transformer layers from per-layer weight lists (reference
    fused_transformer.py:872), the functional twin of
    incubate.nn.FusedMultiTransformer. The no-cache forward is implemented
    here; cached decode (cache_kvs/time_step) lives on the layer class,
    which carries the KV-cache state."""
    if cache_kvs is not None or time_step is not None or pre_caches is not None:
        raise NotImplementedError(
            "cached decode: use incubate.nn.FusedMultiTransformer "
            "(gen_cache + time_step)")
    L = len(qkv_weights)
    out = x
    for i in range(L):
        qw = as_tensor(qkv_weights[i])
        # trans_qkvw=True stores [3, H, D, E]; False stores [E, 3, H, D]
        if not trans_qkvw:
            qw = Tensor(jnp.transpose(qw._value, (1, 2, 3, 0)))
        out = fused_multi_head_attention(
            out, qw, linear_weights[i], pre_layer_norm=pre_layer_norm,
            pre_ln_scale=ln_scales[i] if ln_scales else None,
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            ln_scale=ln_scales[i] if ln_scales else None,
            ln_bias=ln_biases[i] if ln_biases else None,
            pre_ln_epsilon=epsilon,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, ln_epsilon=epsilon,
            training=training, add_residual=True)
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            ln2_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln2_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, ln1_epsilon=epsilon, ln2_epsilon=epsilon,
            pre_layer_norm=pre_layer_norm, training=training,
            add_residual=True)
    return as_tensor(out)
