"""ASP pruning workflow: prune_model + optimizer decoration.

Reference surface: python/paddle/incubate/asp/asp.py — ASPHelper keeps a
per-parameter mask registry; ``prune_model`` computes n:m masks for supported
layers (Linear/Conv2D weights) and applies them in place; ``decorate`` wraps
an optimizer so masks are re-applied after every step (the sparsity
guarantee); ``set_excluded_layers`` opts layers out by name.
"""

from __future__ import annotations

import numpy as np

from .utils import CheckMethod, MaskAlgo, create_mask

_EXCLUDED = set()


class ASPHelper:
    MASK_APPENDDED_NAME = "asp_mask"
    _masks: dict = {}  # param name -> numpy mask

    @classmethod
    def _supported(cls, model, param, param_name: str) -> bool:
        if param_name in _EXCLUDED:
            return False
        for ex in _EXCLUDED:
            if param_name.startswith(ex + ".") or param_name.split(".")[0] == ex:
                return False
        # weights of Linear (2-D) and Conv (4-D); skip biases / norms / embeddings
        shape = param.shape
        if len(shape) not in (2, 4):
            return False
        flat_cols = int(np.prod(shape[1:]))
        return shape[0] >= 4 and flat_cols >= 4 and "embed" not in param_name.lower()

    @classmethod
    def prune_model(cls, model, n: int = 2, m: int = 4, mask_algo: MaskAlgo = MaskAlgo.MASK_1D, with_mask: bool = True):
        from ...ops.creation import to_tensor

        masks = {}
        for name, param in model.named_parameters():
            if not cls._supported(model, param, name):
                continue
            w = np.asarray(param._value, dtype=np.float32)
            mask = create_mask(w, func_name=mask_algo, n=n, m=m)
            param._set_value_raw(to_tensor((w * mask).astype(w.dtype))._value)
            if with_mask:
                masks[name] = mask
        cls._masks = masks
        return masks

    @classmethod
    def decorate(cls, optimizer):
        return OptimizerWithSparsityGuarantee(optimizer)


class OptimizerWithSparsityGuarantee:
    """After each optimizer step, re-multiply masked params by their mask so
    pruned weights stay exactly zero through training."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        if not ASPHelper._masks:
            return
        from ...ops.creation import to_tensor

        params = (getattr(self._optimizer, "_parameter_list", None)
                  or getattr(self._optimizer, "_parameters", None) or [])
        for p in params:
            key = getattr(p, "_asp_mask_key", None)
            if key is not None and key in ASPHelper._masks:
                mask = ASPHelper._masks[key]
                w = np.asarray(p._value)
                p._set_value_raw(to_tensor((w * mask).astype(w.dtype))._value)


def set_excluded_layers(param_names, main_program=None):
    for n in param_names:
        _EXCLUDED.add(n)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d", with_mask: bool = True):
    algo = {
        "mask_1d": MaskAlgo.MASK_1D,
        "mask_2d_greedy": MaskAlgo.MASK_2D_GREEDY,
        "mask_2d_best": MaskAlgo.MASK_2D_BEST,
    }[mask_algo]
    masks = ASPHelper.prune_model(model, n=n, m=m, mask_algo=algo, with_mask=with_mask)
    # tag parameters so the decorated optimizer can find their masks
    for name, param in model.named_parameters():
        if name in masks:
            param._asp_mask_key = name
    return masks


def decorate(optimizer):
    return ASPHelper.decorate(optimizer)
