"""ASP pruning workflow: prune_model + optimizer decoration.

Reference surface: python/paddle/incubate/asp/asp.py — ASPHelper keeps a
per-parameter mask registry; ``prune_model`` computes n:m masks for supported
layers (Linear/Conv2D weights) and applies them in place; ``decorate`` wraps
an optimizer so masks are re-applied after every step (the sparsity
guarantee); ``set_excluded_layers`` opts layers out by name.
"""

from __future__ import annotations

import numpy as np

from .utils import CheckMethod, MaskAlgo, create_mask

_EXCLUDED = set()


class ASPHelper:
    MASK_APPENDDED_NAME = "asp_mask"
    _masks: dict = {}  # param name -> numpy mask

    @staticmethod
    def _owner_types(model) -> dict:
        """{owner-prefix: class name} over the model, incl. the root as ''
        — built ONCE per prune, not re-scanned per parameter."""
        owners = {"": type(model).__name__}
        for sub_name, sub in model.named_sublayers():
            owners[sub_name] = type(sub).__name__
        return owners

    @classmethod
    def _supported(cls, model, param, param_name: str, owners=None) -> bool:
        if param_name in _EXCLUDED:
            return False
        for ex in _EXCLUDED:
            if param_name.startswith(ex + ".") or param_name.split(".")[0] == ex:
                return False
        shape = param.shape
        # custom-registered layer types (add_supported_layer) win over the
        # built-in heuristic — match by the owning layer's class name
        if _CUSTOM_SUPPORTED and model is not None:
            owners = owners if owners is not None else cls._owner_types(model)
            owner = param_name.rsplit(".", 1)[0] if "." in param_name else ""
            if owners.get(owner) in _CUSTOM_SUPPORTED:
                return len(shape) >= 2
        # weights of Linear (2-D) and Conv (4-D); skip biases / norms / embeddings
        if len(shape) not in (2, 4):
            return False
        flat_cols = int(np.prod(shape[1:]))
        return shape[0] >= 4 and flat_cols >= 4 and "embed" not in param_name.lower()

    @classmethod
    def prune_model(cls, model, n: int = 2, m: int = 4, mask_algo: MaskAlgo = MaskAlgo.MASK_1D, with_mask: bool = True):
        from ...ops.creation import to_tensor

        masks = {}
        owners = cls._owner_types(model)
        for name, param in model.named_parameters():
            if not cls._supported(model, param, name, owners=owners):
                continue
            w = np.asarray(param._value, dtype=np.float32)
            # a custom pruning_func registered for the owning layer type
            # overrides the built-in n:m mask (add_supported_layer contract)
            owner = name.rsplit(".", 1)[0] if "." in name else ""
            custom = _CUSTOM_SUPPORTED.get(owners.get(owner))
            if custom is not None:
                mask = np.asarray(custom(w, n, m, mask_algo), w.dtype)
            else:
                mask = create_mask(w, func_name=mask_algo, n=n, m=m)
            param._set_value_raw(to_tensor((w * mask).astype(w.dtype))._value)
            if with_mask:
                masks[name] = mask
        cls._masks = masks
        return masks

    @classmethod
    def decorate(cls, optimizer):
        return OptimizerWithSparsityGuarantee(optimizer)


class OptimizerWithSparsityGuarantee:
    """After each optimizer step, re-multiply masked params by their mask so
    pruned weights stay exactly zero through training."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        if not ASPHelper._masks:
            return
        from ...ops.creation import to_tensor

        params = (getattr(self._optimizer, "_parameter_list", None)
                  or getattr(self._optimizer, "_parameters", None) or [])
        for p in params:
            key = getattr(p, "_asp_mask_key", None)
            if key is not None and key in ASPHelper._masks:
                mask = ASPHelper._masks[key]
                w = np.asarray(p._value)
                p._set_value_raw(to_tensor((w * mask).astype(w.dtype))._value)


def set_excluded_layers(param_names, main_program=None):
    for n in param_names:
        _EXCLUDED.add(n)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d", with_mask: bool = True):
    algo = {
        "mask_1d": MaskAlgo.MASK_1D,
        "mask_2d_greedy": MaskAlgo.MASK_2D_GREEDY,
        "mask_2d_best": MaskAlgo.MASK_2D_BEST,
    }[mask_algo]
    masks = ASPHelper.prune_model(model, n=n, m=m, mask_algo=algo, with_mask=with_mask)
    # tag parameters so the decorated optimizer can find their masks
    for name, param in model.named_parameters():
        if name in masks:
            param._asp_mask_key = name
    return masks


def decorate(optimizer):
    return ASPHelper.decorate(optimizer)


#: layer types registered as prunable beyond the built-in Linear/Conv
#: heuristic (reference asp add_supported_layer)
_CUSTOM_SUPPORTED: dict = {}


def add_supported_layer(layer, pruning_func=None):
    """Register a custom layer type (class or its name string) whose
    weights ASP should prune; `pruning_func(weight, n, m, mask_algo)` may
    override mask computation (reference
    incubate/asp/supported_layer_list.py)."""
    name = layer if isinstance(layer, str) else getattr(
        layer, "__name__", str(layer))
    _CUSTOM_SUPPORTED[name] = pruning_func
