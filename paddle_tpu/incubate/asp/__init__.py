"""ASP: automatic 2:4 structured sparsity (n:m sparse pruning).

Reference surface: python/paddle/incubate/asp/ (asp.py prune_model/decorate/
set_excluded_layers, utils.py mask generators & checkers). On TPU the mask is
a plain elementwise multiply fused into the matmul by XLA; sparse-tensor-core
style acceleration is not modeled, but mask semantics, optimizer guarantees,
and checkers match the reference.
"""

from .asp import (  # noqa: F401
    add_supported_layer,
    ASPHelper,
    decorate,
    prune_model,
    reset_excluded_layers,
    set_excluded_layers,
)
from .utils import (  # noqa: F401
    CheckMethod,
    MaskAlgo,
    calculate_density,
    check_mask_1d,
    check_mask_2d,
    check_sparsity,
    create_mask,
    get_mask_1d,
    get_mask_2d_greedy,
)

__all__ = [
    "add_supported_layer",
    "calculate_density",
    "decorate",
    "prune_model",
    "set_excluded_layers",
    "reset_excluded_layers",
]
