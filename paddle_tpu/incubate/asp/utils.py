"""n:m sparsity mask generation and validation (host-side numpy).

Reference surface: python/paddle/incubate/asp/utils.py — get_mask_1d
(keep the n largest of every m contiguous elements along rows),
get_mask_2d_greedy, check_mask_1d/2d, create_mask, check_sparsity,
calculate_density. Mask computation is an offline pruning pass, so it stays
in numpy; only the masked multiply runs on device.
"""

from __future__ import annotations

from enum import Enum
from itertools import permutations

import numpy as np

__all__ = [
    "MaskAlgo",
    "CheckMethod",
    "calculate_density",
    "get_mask_1d",
    "get_mask_2d_greedy",
    "check_mask_1d",
    "check_mask_2d",
    "create_mask",
    "check_sparsity",
]


class MaskAlgo(Enum):
    MASK_1D = "get_mask_1d"
    MASK_2D_GREEDY = "get_mask_2d_greedy"
    MASK_2D_BEST = "get_mask_2d_best"


class CheckMethod(Enum):
    CHECK_1D = "check_mask_1d"
    CHECK_2D = "check_mask_2d"

    @staticmethod
    def get_checking_method(mask_algo: MaskAlgo):
        return CheckMethod.CHECK_1D if mask_algo == MaskAlgo.MASK_1D else CheckMethod.CHECK_2D


def calculate_density(x) -> float:
    a = np.asarray(x)
    return float(np.count_nonzero(a)) / a.size


def _reshape_1d(mat: np.ndarray, m: int):
    """Pad the row length up to a multiple of m and view as groups of m."""
    rows, cols = mat.shape
    pad = (m - cols % m) % m
    padded = np.concatenate([mat, np.zeros((rows, pad), mat.dtype)], axis=1) if pad else mat
    return padded.reshape(-1, m), padded.shape


def get_mask_1d(mat: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest-|.|-valued of every m contiguous elements per row."""
    mat = np.asarray(mat)
    groups, padded_shape = _reshape_1d(mat, m)
    mask = np.zeros_like(groups)
    idx = np.argsort(np.abs(groups), axis=1)[:, -n:]
    np.put_along_axis(mask, idx, 1.0, axis=1)
    mask = mask.reshape(padded_shape)[: mat.shape[0], : mat.shape[1]]
    return mask.astype(mat.dtype)


def check_mask_1d(mat: np.ndarray, n: int, m: int) -> bool:
    """True iff every m-contiguous group per row has at most (m-n) nonzeros...
    i.e. at least (m-n) zeros — the n:m sparse property along rows."""
    mat = np.asarray(mat)
    groups, _ = _reshape_1d(mat, m)
    return bool(np.all((groups != 0).sum(axis=1) <= n))


def get_mask_2d_greedy(mat: np.ndarray, n: int, m: int) -> np.ndarray:
    """Greedy 2-D n:m mask: in every m x m tile keep entries maximizing
    magnitude subject to <=n nonzeros per row AND per column of the tile."""
    mat = np.asarray(mat)
    rows, cols = mat.shape
    pr, pc = (m - rows % m) % m, (m - cols % m) % m
    padded = np.pad(np.abs(mat), ((0, pr), (0, pc)))
    mask = np.zeros_like(padded)
    for r0 in range(0, padded.shape[0], m):
        for c0 in range(0, padded.shape[1], m):
            tile = padded[r0:r0 + m, c0:c0 + m]
            sub_mask = np.zeros((m, m))
            order = np.argsort(-tile, axis=None)
            row_cnt, col_cnt = np.zeros(m, int), np.zeros(m, int)
            for flat in order:
                i, j = divmod(int(flat), m)
                if row_cnt[i] < n and col_cnt[j] < n:
                    sub_mask[i, j] = 1.0
                    row_cnt[i] += 1
                    col_cnt[j] += 1
            mask[r0:r0 + m, c0:c0 + m] = sub_mask
    return mask[:rows, :cols].astype(mat.dtype)


def check_mask_2d(mat: np.ndarray, n: int, m: int) -> bool:
    mat = np.asarray(mat)
    rows, cols = mat.shape
    pr, pc = (m - rows % m) % m, (m - cols % m) % m
    padded = np.pad(mat, ((0, pr), (0, pc)))
    for r0 in range(0, padded.shape[0], m):
        for c0 in range(0, padded.shape[1], m):
            tile = padded[r0:r0 + m, c0:c0 + m] != 0
            if tile.sum(axis=0).max(initial=0) > n or tile.sum(axis=1).max(initial=0) > n:
                return False
    return True


def _as_2d(t: np.ndarray):
    """Collapse leading dims: conv [oc,ic,kh,kw] -> [oc, ic*kh*kw]; keep 2-D."""
    if t.ndim == 1:
        return t.reshape(1, -1), t.shape
    if t.ndim > 2:
        return t.reshape(t.shape[0], -1), t.shape
    return t, t.shape


def create_mask(tensor, func_name: MaskAlgo = MaskAlgo.MASK_1D, n: int = 2, m: int = 4) -> np.ndarray:
    t = np.asarray(tensor)
    mat, orig_shape = _as_2d(t)
    if func_name == MaskAlgo.MASK_1D:
        mask = get_mask_1d(mat, n, m)
    elif func_name in (MaskAlgo.MASK_2D_GREEDY, MaskAlgo.MASK_2D_BEST):
        mask = get_mask_2d_greedy(mat, n, m)
    else:
        raise ValueError(f"unknown mask algo {func_name}")
    return mask.reshape(orig_shape)


def check_sparsity(tensor, func_name: CheckMethod = CheckMethod.CHECK_1D, n: int = 2, m: int = 4) -> bool:
    t = np.asarray(tensor)
    mat, _ = _as_2d(t)
    return check_mask_1d(mat, n, m) if func_name == CheckMethod.CHECK_1D else check_mask_2d(mat, n, m)
