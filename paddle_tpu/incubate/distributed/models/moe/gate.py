"""MoE gates (incubate/distributed/models/moe/gate/ analog): GShard top-2 and
Switch top-1 as pure capacity-based dense dispatch — the einsum/one-hot
formulation XLA partitions into all-to-all instead of the reference's
index-based global_scatter CUDA op."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _positions_in_expert(mask):
    """mask: [T, E] 0/1 -> position of each token within its expert queue."""
    return (jnp.cumsum(mask, axis=0) - 1) * mask


def switch_gating(logits, capacity: int):
    """Top-1 (Switch) gate. Returns (dispatch [T,E,C] f32, combine [T,E,C] f32, aux_loss)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [T]
    mask = jax.nn.one_hot(expert, E, dtype=jnp.float32)
    # load-balancing aux loss (Switch eq. 4)
    density = mask.mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux = (density * density_proxy).sum() * E
    pos = _positions_in_expert(mask)
    keep = (pos < capacity) * mask
    gate_w = (probs * keep).sum(axis=-1)  # [T]
    dispatch = keep[..., None] * jax.nn.one_hot(pos.sum(axis=-1).astype(jnp.int32), capacity, dtype=jnp.float32)[:, None, :]
    combine = dispatch * gate_w[:, None, None]
    return dispatch, combine, aux


def gshard_gating(logits, capacity: int):
    """Top-2 (GShard) gate."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    g1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(g1, E, dtype=jnp.float32)
    probs2 = probs * (1 - mask1)
    g2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(g2, E, dtype=jnp.float32)

    density = mask1.mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux = (density * density_proxy).sum() * E

    pos1 = _positions_in_expert(mask1)
    used1 = mask1.sum(axis=0, keepdims=True)  # tokens ahead from top-1 round
    pos2 = _positions_in_expert(mask2) + used1 * mask2
    keep1 = (pos1 < capacity) * mask1
    keep2 = (pos2 < capacity) * mask2

    w1 = (probs * keep1).sum(axis=-1)
    w2 = (probs * keep2).sum(axis=-1)
    denom = jnp.maximum(w1 + w2, 1e-9)
    w1, w2 = w1 / denom, w2 / denom

    def disp(keep, pos):
        return keep[..., None] * jax.nn.one_hot((pos * keep).sum(axis=-1).astype(jnp.int32), capacity, dtype=jnp.float32)[:, None, :]

    d1, d2 = disp(keep1, pos1), disp(keep2, pos2)
    dispatch = jnp.clip(d1 + d2, 0.0, 1.0)
    combine = d1 * w1[:, None, None] + d2 * w2[:, None, None]
    return dispatch, combine, aux


class BaseGate:
    def __init__(self, d_model: int, num_experts: int):
        self.d_model = d_model
        self.num_experts = num_experts


class SwitchGate(BaseGate):
    top_k = 1
    gating = staticmethod(switch_gating)


class GShardGate(BaseGate):
    top_k = 2
    gating = staticmethod(gshard_gating)
