"""MoELayer (incubate/distributed/models/moe/moe_layer.py:261 analog).

The reference routes tokens with index-based global_scatter/global_gather
all-to-all CUDA ops. TPU-native, routing is the dense GShard formulation:
capacity-bounded one-hot dispatch/combine tensors and einsums — static
shapes, MXU-friendly, and under a mesh the expert dimension sharded over the
`ep` axis makes XLA emit exactly the all-to-all pair the reference wrote by
hand. `aux_loss` carries the load-balancing term (reference's gate loss).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .....core.tensor import Tensor
from .....nn.layer.layers import Layer
from .....distributed.sharding_utils import maybe_shard
from .....ops._dispatch import apply
from .gate import GShardGate, SwitchGate, gshard_gating, switch_gating

EP_AXIS = "ep"


def moe_route(xt, gate_weight, gate_type: str, capacity: int, run_experts,
              dispatch_mode: str = "dense", quant_block: int = 128):
    """Shared dense-routing core (GShard/Switch): gate -> dispatch einsum ->
    run_experts([E, C, d] -> [E, C, d'], ep-sharded) -> combine einsum.
    Both MoELayer and models.gpt.GPTMoEMLP route through here, so capacity/
    overflow/gating semantics cannot diverge. Returns (out [T, d'], aux).

    dispatch_mode "quant" compresses the two cross-ep token exchanges to
    block-scaled int8 (dispatch.py); gating, capacity assignment and the
    aux loss stay full precision, so routing is identical to dense and
    outputs differ only by the wire format's quantization noise. Contexts
    that cannot host the compressed all-to-all fall back to dense and
    record the `moe-dispatch-downgrade` ambient finding."""
    if dispatch_mode not in ("dense", "quant"):
        raise ValueError(
            f"dispatch_mode must be 'dense' or 'quant', got {dispatch_mode!r}")
    logits = xt.matmul(gate_weight)  # [T, E]
    gating = gshard_gating if gate_type == "gshard" else switch_gating
    dispatch, combine, aux = apply(
        "moe_gating", lambda lg: gating(lg, capacity), logits)

    plan = None
    if dispatch_mode == "quant":
        from .dispatch import plan_quant_dispatch, quant_combine, quant_dispatch

        plan = plan_quant_dispatch(int(xt.shape[0]),
                                   int(gate_weight.shape[-1]), capacity,
                                   int(xt.shape[-1]), block=quant_block)

    if plan is not None:
        ein = apply("moe_dispatch_quant",
                    lambda dv, xv: quant_dispatch(plan, dv, xv), dispatch, xt)
    else:
        def dispatch_fn(dv, xv):
            return jnp.einsum("tec,td->ecd", dv,
                              xv.astype(jnp.float32)).astype(xv.dtype)

        ein = apply("moe_dispatch", dispatch_fn, dispatch, xt)  # [E, C, d]
    ein = maybe_shard(ein, P(EP_AXIS, None, None))
    eout = maybe_shard(run_experts(ein), P(EP_AXIS, None, None))

    if plan is not None:
        return apply("moe_combine_quant",
                     lambda cv, ev: quant_combine(plan, cv, ev),
                     combine, eout), aux

    def combine_fn(cv, ev):
        return jnp.einsum("tec,ecd->td", cv,
                          ev.astype(jnp.float32)).astype(ev.dtype)

    return apply("moe_combine", combine_fn, combine, eout), aux


class MoELayer(Layer):
    """Mixture of experts over `experts` (a list of same-architecture Layers).

    recompute/capacity semantics follow the reference: capacity =
    cap_factor * T / E per expert, overflow tokens are dropped (contribute 0
    through the residual path).
    """

    def __init__(
        self,
        d_model: int,
        experts: Sequence[Layer],
        gate: str = "gshard",
        top_k: Optional[int] = None,
        capacity_factor: float = 1.25,
        group=None,
        recompute_interval: int = 0,
        dispatch: str = "dense",
        name=None,
    ):
        super().__init__()
        self.dispatch_mode = dispatch
        self.d_model = d_model
        self.num_experts = len(experts)
        self.experts = experts
        for i, e in enumerate(experts):
            self.add_sublayer(f"expert_{i}", e)
        self.capacity_factor = capacity_factor
        if top_k is not None:
            if top_k not in (1, 2):
                raise ValueError(f"top_k must be 1 (switch) or 2 (gshard), got {top_k}")
            self.gate_type = "switch" if top_k == 1 else "gshard"
        elif isinstance(gate, str):
            self.gate_type = gate
        else:
            self.gate_type = "gshard" if getattr(gate, "top_k", 2) == 2 else "switch"
        self.top_k = 1 if self.gate_type == "switch" else 2
        self.gate_weight = self.create_parameter([d_model, self.num_experts])
        self.aux_loss = None

    def _gating(self, logits, capacity):
        fn = gshard_gating if self.gate_type == "gshard" else switch_gating
        return fn(logits, capacity)

    def _fused_expert_stack(self):
        """When every expert is a same-shaped ExpertMLP, return stacked
        (w1, b1, w2, b2, act) Tensors [E, ...] sharded over ep; else None."""
        if not all(type(e) is ExpertMLP for e in self.experts):
            return None
        e0 = self.experts[0]
        shapes = (e0.fc1.weight.shape, e0.fc2.weight.shape)
        if not all((e.fc1.weight.shape, e.fc2.weight.shape) == shapes
                   and e._act_name == e0._act_name for e in self.experts):
            return None
        from ..... import ops as _ops

        w1 = maybe_shard(_ops.stack([e.fc1.weight for e in self.experts], axis=0), P(EP_AXIS, None, None))
        b1 = maybe_shard(_ops.stack([e.fc1.bias for e in self.experts], axis=0), P(EP_AXIS, None))
        w2 = maybe_shard(_ops.stack([e.fc2.weight for e in self.experts], axis=0), P(EP_AXIS, None, None))
        b2 = maybe_shard(_ops.stack([e.fc2.bias for e in self.experts], axis=0), P(EP_AXIS, None))
        import jax.nn as jnn

        # match nn.functional defaults (paddle gelu is exact, not tanh-approx)
        acts = {"gelu": lambda x: jnn.gelu(x, approximate=False), "relu": jnn.relu,
                "silu": jnn.silu, "sigmoid": jnn.sigmoid, "tanh": jnp.tanh}
        act = acts.get(e0._act_name)
        if act is None:
            return None
        return w1, b1, w2, b2, act

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        xt = x.reshape([-1, d])  # [T, d]
        T = xt.shape[0]
        capacity = max(1, int(self.capacity_factor * T / self.num_experts))

        fused = self._fused_expert_stack()
        if fused is not None:
            # homogeneous ExpertMLPs: run all experts as ONE batched einsum
            # over stacked weights sharded on the ep axis — expert compute
            # stays on the owning devices and XLA emits the all-to-all pair
            # around the dispatch/combine einsums (global_scatter/gather
            # analog, verified by tests/test_hlo_collectives.py)
            w1, b1, w2, b2, act = fused

            def run_experts(expert_in):
                def experts_fn(ei, w1v, b1v, w2v, b2v):
                    h = jnp.einsum("ecd,edh->ech", ei.astype(jnp.float32), w1v.astype(jnp.float32))
                    h = act(h + b1v[:, None, :])
                    o = jnp.einsum("ech,ehd->ecd", h, w2v.astype(jnp.float32))
                    return (o + b2v[:, None, :]).astype(ei.dtype)

                return apply("moe_experts_fused", experts_fn, expert_in, w1, b1, w2, b2)
        else:
            def run_experts(expert_in):
                from ..... import ops as _ops

                return _ops.stack([e(expert_in[i]) for i, e in enumerate(self.experts)], axis=0)

        out, aux = moe_route(xt, self.gate_weight, self.gate_type, capacity,
                             run_experts, dispatch_mode=self.dispatch_mode)
        self.aux_loss = aux
        return out.reshape(orig_shape[:-1] + [out.shape[-1]])


class ExpertMLP(Layer):
    """Default FFN expert (the reference's ExpertLayer)."""

    def __init__(self, d_model: int, d_hidden: int, activation: str = "gelu"):
        super().__init__()
        from ..... import nn

        self.fc1 = nn.Linear(d_model, d_hidden)
        self.fc2 = nn.Linear(d_hidden, d_model)
        self._act_name = activation
        self.act = getattr(nn.functional, activation)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def _host_counts(c):
    import numpy as np

    if isinstance(c, Tensor):
        c = c.numpy()
    return np.asarray(c).astype(np.int64)


def global_scatter(x, local_count, global_count, group=None):
    """Count-routed token exchange (operators/collective/global_scatter_op.cu.cc
    analog). x: [T, d] rows grouped in chunks sized local_count[i] (i over
    world*n_local global experts, rank-major: chunk i targets rank
    i // n_local's local expert i % n_local). The receiver's rows are ordered
    local-expert-major then source-rank — the layout global_gather inverts.

    Eager single-controller form: with world==1 this is the identity routing;
    with the per-rank stacked convention ([N, T, d] + [N, E] counts) the
    routing runs host-side on the gathered views. The performant jit path is
    the dense dispatch-einsum in MoELayer (XLA emits the all-to-all)."""
    import numpy as np

    from .....distributed.communication import _resolve_group, rank_slices

    g = _resolve_group(group)
    if g.nranks == 1:
        return x
    lcs = _host_counts(local_count).reshape(g.nranks, -1)  # [N, world*n_local] per-rank
    n_local = lcs.shape[1] // g.nranks
    xs = [np.asarray(t.numpy()) for t in (rank_slices(x) if isinstance(x, Tensor) else x)]
    # split each sender's rows into per-(dest rank, local expert) chunks
    chunks = []
    for r in range(g.nranks):
        offs = np.concatenate([[0], np.cumsum(lcs[r])])
        chunks.append([xs[r][offs[i] : offs[i + 1]] for i in range(lcs.shape[1])])
    out: List = []
    for q in range(g.nranks):
        rows = [chunks[s][q * n_local + e] for e in range(n_local) for s in range(g.nranks)]
        out.append(Tensor(jnp.asarray(np.concatenate(rows, axis=0))))
    return out


def global_gather(x, local_count, global_count, group=None):
    """Inverse of global_scatter: returns each rank's rows to their source in
    original chunk order (global_gather_op.cu.cc analog)."""
    import numpy as np

    from .....distributed.communication import _resolve_group, rank_slices

    g = _resolve_group(group)
    if g.nranks == 1:
        return x
    lcs = _host_counts(local_count).reshape(g.nranks, -1)
    n_local = lcs.shape[1] // g.nranks
    xs = [np.asarray(t.numpy()) for t in (rank_slices(x) if isinstance(x, Tensor) else x)]
    # receiver q's buffer is ordered (e, s) with sizes lcs[s, q*n_local+e]
    recv_chunks: dict = {}
    for q in range(g.nranks):
        off = 0
        for e in range(n_local):
            for s in range(g.nranks):
                sz = int(lcs[s, q * n_local + e])
                recv_chunks[(s, q * n_local + e)] = xs[q][off : off + sz]
                off += sz
    out: List = []
    for r in range(g.nranks):
        rows = [recv_chunks[(r, i)] for i in range(lcs.shape[1])]
        out.append(Tensor(jnp.asarray(np.concatenate(rows, axis=0))))
    return out
