"""MoELayer (incubate/distributed/models/moe/moe_layer.py:261 analog).

The reference routes tokens with index-based global_scatter/global_gather
all-to-all CUDA ops. TPU-native, routing is the dense GShard formulation:
capacity-bounded one-hot dispatch/combine tensors and einsums — static
shapes, MXU-friendly, and under a mesh the expert dimension sharded over the
`ep` axis makes XLA emit exactly the all-to-all pair the reference wrote by
hand. `aux_loss` carries the load-balancing term (reference's gate loss).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .....core.tensor import Tensor
from .....nn.layer.layers import Layer
from .....distributed.sharding_utils import annotate_parameter, maybe_shard
from .....ops._dispatch import apply, as_tensor
from .gate import GShardGate, SwitchGate, gshard_gating, switch_gating

EP_AXIS = "ep"


class MoELayer(Layer):
    """Mixture of experts over `experts` (a list of same-architecture Layers).

    recompute/capacity semantics follow the reference: capacity =
    cap_factor * T / E per expert, overflow tokens are dropped (contribute 0
    through the residual path).
    """

    def __init__(
        self,
        d_model: int,
        experts: Sequence[Layer],
        gate: str = "gshard",
        top_k: Optional[int] = None,
        capacity_factor: float = 1.25,
        group=None,
        recompute_interval: int = 0,
        name=None,
    ):
        super().__init__()
        self.d_model = d_model
        self.num_experts = len(experts)
        self.experts = experts
        for i, e in enumerate(experts):
            self.add_sublayer(f"expert_{i}", e)
        self.capacity_factor = capacity_factor
        if isinstance(gate, str):
            self.gate_type = gate
        else:
            self.gate_type = "gshard" if getattr(gate, "top_k", 2) == 2 else "switch"
        self.gate_weight = self.create_parameter([d_model, self.num_experts])
        self.aux_loss = None
        # expert params live on their ep shard
        for i, e in enumerate(experts):
            for _, p in e.named_parameters():
                if p is not None and getattr(p, "dist_spec", None) in (None, P()):
                    p.expert_idx = i

    def _gating(self, logits, capacity):
        fn = gshard_gating if self.gate_type == "gshard" else switch_gating
        return fn(logits, capacity)

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        xt = x.reshape([-1, d])  # [T, d]
        T = xt.shape[0]
        E = self.num_experts
        capacity = max(1, int(self.capacity_factor * T / E))

        logits = xt.matmul(self.gate_weight)  # [T, E]

        gate_type = self.gate_type

        def gating_fn(lg):
            return (gshard_gating if gate_type == "gshard" else switch_gating)(lg, capacity)

        dispatch, combine, aux = apply("moe_gating", gating_fn, logits)
        self.aux_loss = aux

        # expert_in[e] = sum_t dispatch[t,e,c] * x[t]  -> [E, C, d]
        def dispatch_fn(dv, xv):
            return jnp.einsum("tec,td->ecd", dv, xv.astype(jnp.float32)).astype(xv.dtype)

        expert_in = apply("moe_dispatch", dispatch_fn, dispatch, xt)  # [E, C, d]
        expert_in = maybe_shard(expert_in, P(EP_AXIS, None, None))

        outs = []
        for i, e in enumerate(self.experts):
            outs.append(e(expert_in[i]))
        from ..... import ops as _ops

        expert_out = _ops.stack(outs, axis=0)  # [E, C, d_out]
        expert_out = maybe_shard(expert_out, P(EP_AXIS, None, None))

        def combine_fn(cv, ev):
            return jnp.einsum("tec,ecd->td", cv, ev.astype(jnp.float32)).astype(ev.dtype)

        out = apply("moe_combine", combine_fn, combine, expert_out)
        return out.reshape(orig_shape[:-1] + [expert_out.shape[-1]])


class ExpertMLP(Layer):
    """Default FFN expert (the reference's ExpertLayer)."""

    def __init__(self, d_model: int, d_hidden: int, activation: str = "gelu"):
        super().__init__()
        from ..... import nn

        self.fc1 = nn.Linear(d_model, d_hidden)
        self.fc2 = nn.Linear(d_hidden, d_model)
        self.act = getattr(nn.functional, activation)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def global_scatter(x, local_count, global_count, group=None):
    """API-parity analog of operators/collective/global_scatter_op: in the
    dense formulation this is the dispatch einsum + all_to_all; kept as a thin
    named wrapper over communication.alltoall for migrating users."""
    from .....distributed.communication import alltoall

    out: List = []
    alltoall(x, out, group=group)
    return out


def global_gather(x, local_count, global_count, group=None):
    from .....distributed.communication import alltoall

    out: List = []
    alltoall(x, out, group=group)
    return out
