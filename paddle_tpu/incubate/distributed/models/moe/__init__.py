from .gate import GShardGate, SwitchGate, gshard_gating, switch_gating  # noqa: F401
from .moe_layer import ExpertMLP, MoELayer, global_gather, global_scatter  # noqa: F401
