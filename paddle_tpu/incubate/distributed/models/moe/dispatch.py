"""Compressed MoE token dispatch: block-scaled int8 all-to-alls.

The dense GShard routing in moe_layer.py leaves the dispatch/combine
exchanges to GSPMD, which moves the [E, C, d] expert inputs and outputs
between ep ranks at full activation precision. This module is the
`moe_dispatch="quant"` path: the same routing math (gate logits, capacity
assignment and the aux loss stay full precision, so routing decisions are
bit-identical to dense), but the two cross-ep exchanges ride the
kernels/quant.py wire format — int8 payload with an f32 scale sidecar per
`block` trailing elements, ~3.9x fewer wire bytes at block 128.

Forward exchanges:
  dispatch: each rank contracts its LOCAL tokens against the (global,
    full-precision) dispatch one-hots into a partial [E, C, d] expert
    stack, reshapes E into [nep, E_loc], and all-to-alls the int8 payload
    over ep; summing the received per-source partials yields this rank's
    [E_loc, C, d] — a compressed reduce-scatter. Partials from the OTHER
    data axes (dp/sharding) are summed outside the manual region by GSPMD
    (same fp32 [E, C, d] reduction the dense path already pays).
  combine: each rank quantizes its local expert outputs and all-gathers
    them over ep; the combine einsum then runs on local tokens.

Backward is the transposed exchange, also compressed: the 0/0 all-to-all
permutation is its own transpose, and the all-gather transposes to the
quantized reduce-scatter above. The round/clip nonlinearity uses the
straight-through estimator — cotangents pass through the quantizer's wire
format but not its derivative (which is zero a.e.).

Context rules mirror comm_opt's reducer activation (see plan_quant_dispatch):
GSPMD-auto ambient opens a fully-manual shard_map island; a fully-manual
ambient (the flat explicit-grad-reduce step) runs the exchange body
directly with lax collectives; a PARTIAL-manual ambient (pipeline stages,
the hybrid reducer's region A) cannot host the all-to-all, so the layer
falls back to dense routing and records the `moe-dispatch-downgrade`
ambient finding — the analyzer-visible record that wire bytes silently
reverted to full precision.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .....kernels.quant import (dequantize_block_scaled, fit_block_size,
                                quantize_block_scaled)
from .....distributed.sharding_utils import DATA_AXES

EP_AXIS = "ep"

#: Below this block size the f32 scale sidecar eats the compression
#: (wire = 1 + 4/block bytes per value; block 8 is the 1.5x break-even
#: territory) — plan_quant_dispatch downgrades instead.
MIN_BLOCK = 8


# ---------------------------------------------------------------------------
# quantized exchange primitives (custom VJP, both directions compressed)
# ---------------------------------------------------------------------------

def _quant_a2a(x, axis_name: str, block_size: int):
    """dequant(all_to_all(quant(x))) over dim 0; x [n, ..., C] with n the
    axis size, C a block multiple. Returns f32 [n(source-major), ..., C]."""
    q, s = quantize_block_scaled(x, block_size)
    qr = lax.all_to_all(q, axis_name, 0, 0)
    sr = lax.all_to_all(s, axis_name, 0, 0)
    return dequantize_block_scaled(qr, sr, block_size)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def quant_all_to_all(x, axis_name: str, block_size: int):
    """Compressed all-to-all: int8 payload + f32 scales on the wire, f32
    out. Call inside a region manual over `axis_name`."""
    return _quant_a2a(x, axis_name, block_size)


def _qa2a_fwd(x, axis_name, block_size):
    return _quant_a2a(x, axis_name, block_size), None


def _qa2a_bwd(axis_name, block_size, _res, ct):
    # the (split=0, concat=0) all-to-all is a self-transpose permutation of
    # (rank, chunk) pairs; straight-through the quantizer and compress the
    # backward wire the same way as forward
    return (_quant_a2a(ct, axis_name, block_size),)


quant_all_to_all.defvjp(_qa2a_fwd, _qa2a_bwd)


def _quant_ag(x, axis_name: str, block_size: int):
    q, s = quantize_block_scaled(x, block_size)
    qg = lax.all_gather(q, axis_name, axis=0, tiled=True)
    sg = lax.all_gather(s, axis_name, axis=0, tiled=True)
    return dequantize_block_scaled(qg, sg, block_size)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def quant_all_gather(x, axis_name: str, block_size: int):
    """Compressed tiled all-gather over dim 0: local [m, ..., C] ->
    f32 [n*m, ..., C]. Transpose is the compressed reduce-scatter."""
    return _quant_ag(x, axis_name, block_size)


def _qag_fwd(x, axis_name, block_size):
    return _quant_ag(x, axis_name, block_size), None


def _qag_bwd(axis_name, block_size, _res, ct):
    # transpose of a tiled all-gather is a reduce-scatter; run it as the
    # compressed all-to-all + local sum over the source dim
    n = lax.psum(1, axis_name)
    cr = ct.reshape((n, ct.shape[0] // n) + ct.shape[1:])
    return (_quant_a2a(cr, axis_name, block_size).sum(axis=0),)


quant_all_gather.defvjp(_qag_fwd, _qag_bwd)


# ---------------------------------------------------------------------------
# plan: context resolution + static wire accounting
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class DispatchPlan:
    """Resolved quant-dispatch schedule for one MoE layer call."""
    mesh: object                  # mesh hosting the island (None when direct)
    manual_direct: bool           # ambient already fully manual: no island
    axis_names: Tuple[str, ...]   # every mesh axis (the island's manual set)
    data_axes: Tuple[str, ...]    # batch-carrying axes, DATA_AXES order
    nep: int
    block: int
    # per-device RECEIVE-side bytes of the two forward exchanges (payload +
    # scale sidecar) and what the same exchanges move at fp32 — the
    # comm_opt/analysis convention (rules.wire_bytes), so the analyzer's
    # estimate reconciles against this accounting exactly
    bytes_wire: int
    bytes_raw: int

    @property
    def other_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in self.data_axes if a != EP_AXIS)

    @property
    def bytes_wire_train_step(self) -> int:
        """Fwd + transposed-bwd exchanges of one train-step MoE call: the
        backward all-to-alls mirror the forward ones byte-for-byte (the
        all-gather's transpose is the compressed reduce-scatter of the
        same buffer), so a step moves exactly twice the forward wire."""
        return 2 * self.bytes_wire

    @property
    def compression_ratio(self) -> float:
        return self.bytes_raw / self.bytes_wire if self.bytes_wire else 0.0


def _resolve_context():
    """(mesh, {axis: size}, manual_axes, known) of the ambient context.

    Modern jax: the abstract mesh carries axis types, so the manual set is
    exact. This build's 0.4.x shim returns an empty abstract mesh, so fall
    back to the process-global mesh (topology's HybridCommunicateGroup and
    fleet.init register it) and detect "inside a shard_map region" by
    probing the axis environment — legacy jax exposes every region axis
    (manual AND auto) there, so the manual set is unknowable and `known`
    is False: the caller must decide from mesh composition instead.
    """
    try:
        m = jax.sharding.get_abstract_mesh()
        names = tuple(getattr(m, "axis_names", ()) or ())
    except Exception:
        m, names = None, ()
    if names:
        sizes = dict(zip(names, (int(s) for s in m.shape.values())))
        types = dict(zip(names, m.axis_types))
        manual = tuple(a for a, t in types.items()
                       if t == jax.sharding.AxisType.Manual
                       and sizes.get(a, 1) > 1)
        return m, sizes, manual, True
    mesh = None
    try:
        # legacy `with mesh:` thread context — ShardedTrainStep traces its
        # step under jax.set_mesh(mesh), which 0.4.x lowers to this
        from jax._src.mesh import thread_resources

        pm = thread_resources.env.physical_mesh
        if not getattr(pm, "empty", True):
            mesh = pm
    except Exception:
        mesh = None
    if mesh is None:
        from .....distributed.mesh import current_mesh

        mesh = current_mesh()
    if mesh is None:
        return None, {}, (), True
    sizes = dict(zip(mesh.axis_names, (int(s) for s in mesh.devices.shape)))
    in_region = False
    for a in mesh.axis_names:
        try:
            jax.core.axis_frame(a)
            in_region = True
            break
        except Exception:
            continue
    manual = tuple(a for a, s in sizes.items() if s > 1) if in_region else ()
    return mesh, sizes, manual, not in_region


def _downgrade(site: str, message: str, data: Tuple[str, ...]):
    from .....analysis.findings import Finding, record_ambient

    warnings.warn("moe_dispatch='quant' falling back to dense routing: "
                  + message, stacklevel=4)
    record_ambient(Finding(
        rule="moe-dispatch-downgrade", site=site, severity="warning",
        message=("moe_dispatch='quant' silently fell back to dense "
                 "routing (token exchanges move full-precision bytes): "
                 + message),
        data=data))
    _record_metrics(None)
    return None


def _record_metrics(plan: Optional[DispatchPlan]):
    from .....observability import metrics

    if plan is None:
        metrics.counter("moe.dispatch.downgraded")
        return
    metrics.gauge("moe.dispatch.block", plan.block)
    metrics.gauge("moe.dispatch.bytes_wire", plan.bytes_wire)
    metrics.gauge("moe.dispatch.bytes_raw", plan.bytes_raw)
    metrics.gauge("moe.dispatch.compression_ratio", plan.compression_ratio)


def plan_quant_dispatch(T: int, E: int, capacity: int, d: int,
                        block: int = 128, site: str = "moe.moe_route"
                        ) -> Optional[DispatchPlan]:
    """Resolve the ambient mesh context into a DispatchPlan, or None
    meaning "route dense".

    None is SILENT when there is nothing to compress (no ep axis, or ep
    degree 1 — no cross-rank exchange exists). It is a recorded DOWNGRADE
    (`moe-dispatch-downgrade` ambient finding + warning) when an exchange
    exists but cannot run compressed: a partial-manual ambient region
    (pipeline stage / hybrid reducer region A — the all-to-all cannot run
    under partial-auto shard_map), experts indivisible by the ep degree,
    or a model dim whose best block (gcd with `block`) is below MIN_BLOCK.
    """
    mesh, sizes, manual, manual_known = _resolve_context()
    nep = sizes.get(EP_AXIS, 1)
    if mesh is None or nep <= 1:
        return None  # no exchange to compress; dense is exact, not a downgrade
    if E % nep:
        return _downgrade(site, f"{E} experts do not divide the ep degree "
                          f"{nep}", ("indivisible", str(E), str(nep)))
    blk = fit_block_size(d, block)
    if blk < MIN_BLOCK:
        return _downgrade(site, f"model dim {d} admits no quantization "
                          f"block >= {MIN_BLOCK} under block {block}",
                          ("block", str(d), str(block)))
    active = {a for a, s in sizes.items() if s > 1}
    manual = set(manual)
    if manual:
        partial = manual != active
        if not manual_known:
            # legacy-jax in-region fallback: the manual set is unknowable
            # (the axis env exposes auto axes too), so infer from mesh
            # composition — with model/pipeline axes present, the only
            # in-region hosts in this tree are partial-auto (the hybrid
            # reducer's region A, pp/sep stages); data-axes-only meshes
            # host fully-manual regions (the flat explicit-reduce step),
            # where the direct path is safe
            partial = bool(active - set(DATA_AXES))
        if partial:
            # partial-manual: the ep all-to-all cannot run while other
            # axes stay GSPMD-auto — same build constraint that forces
            # comm_opt's two-region schedule
            return _downgrade(site, "ambient region is manual over "
                              f"{sorted(manual)} with other mesh axes "
                              "GSPMD-auto; the compressed all-to-all needs "
                              "a fully-manual (or fully-auto) context",
                              ("partial-manual", ",".join(sorted(manual))))
    dax = tuple(a for a in DATA_AXES if a in active)
    world = int(np.prod([sizes[a] for a in dax], dtype=np.int64))
    if not manual and T % world:
        # the island shards the token dim over every data axis; an
        # indivisible global T cannot open it (manual contexts already
        # hold local shards, so no constraint there)
        return _downgrade(site, f"{T} tokens do not divide the data-axis "
                          f"world {world}", ("indivisible-tokens", str(T),
                                             str(world)))
    e_loc = E // nep
    # receive-side accounting per rules.wire_bytes: the dispatch all-to-all
    # moves the [nep, E_loc, C, d] partial ((nep-1)/nep of it lands on each
    # device's links), the combine all-gather receives every peer's local
    # [E_loc, C, d] — numerically identical per exchange since E = nep*E_loc
    def _recv_a2a(nbytes: int) -> int:
        return (nep - 1) * nbytes // nep

    disp_payload = E * capacity * d                 # int8: 1 byte/value
    disp_scales = 4 * E * capacity * (d // blk)     # f32 sidecar
    wire = (_recv_a2a(disp_payload) + _recv_a2a(disp_scales)
            + (nep - 1) * e_loc * capacity * (d + 4 * (d // blk)))
    raw = _recv_a2a(4 * disp_payload) + (nep - 1) * 4 * e_loc * capacity * d
    plan = DispatchPlan(
        mesh=None if manual else mesh, manual_direct=bool(manual),
        axis_names=tuple(sizes), data_axes=dax, nep=nep, block=blk,
        bytes_wire=wire, bytes_raw=raw)
    _record_metrics(plan)
    return plan


# ---------------------------------------------------------------------------
# the routed exchanges
# ---------------------------------------------------------------------------

def _dispatch_body(plan: DispatchPlan, dv, xv):
    """Local tokens -> this ep rank's [E_loc, C, d] partial (f32), summed
    over ep sources; partials over the other data axes remain."""
    part = jnp.einsum("tec,td->ecd", dv, xv.astype(jnp.float32))
    p4 = part.reshape((plan.nep, part.shape[0] // plan.nep) + part.shape[1:])
    return quant_all_to_all(p4, EP_AXIS, plan.block).sum(axis=0)


def quant_dispatch(plan: DispatchPlan, dv, xv):
    """dispatch one-hots [T, E, C] f32 + tokens [T, d] -> expert inputs
    [E, C, d] (ep-sharded logical view / local shard when manual)."""
    if plan.manual_direct:
        ein = _dispatch_body(plan, dv, xv)
        if plan.other_axes:
            ein = lax.psum(ein, plan.other_axes)
        return ein.astype(xv.dtype)

    bspec = P(plan.data_axes)

    def island(dv_l, xv_l):
        # [1, E_loc, C, d] — the leading stacked dim carries this rank's
        # dp/sharding partial out of the manual region (comm_opt's region-A
        # idiom), so the cross-data-axis sum runs under GSPMD auto and its
        # AD transpose is plain slicing, not a psum transpose
        return _dispatch_body(plan, dv_l, xv_l)[None]

    other = plan.other_axes
    stacked = jax.shard_map(
        island, mesh=plan.mesh, in_specs=(bspec, bspec),
        out_specs=P(other if other else None, EP_AXIS, None, None),
        axis_names=set(plan.axis_names), check_vma=False)(dv, xv)
    return stacked.sum(axis=0).astype(xv.dtype)


def _combine_body(plan: DispatchPlan, cv, ev):
    full = quant_all_gather(ev.astype(jnp.float32), EP_AXIS, plan.block)
    return jnp.einsum("tec,ecd->td", cv, full).astype(ev.dtype)


def quant_combine(plan: DispatchPlan, cv, ev):
    """combine weights [T, E, C] f32 + expert outputs [E, C, d] -> routed
    tokens [T, d]."""
    if plan.manual_direct:
        return _combine_body(plan, cv, ev)
    return jax.shard_map(
        _combine_body_island(plan), mesh=plan.mesh,
        in_specs=(P(plan.data_axes), P(EP_AXIS)),
        out_specs=P(plan.data_axes),
        axis_names=set(plan.axis_names), check_vma=False)(cv, ev)


def _combine_body_island(plan: DispatchPlan):
    def island(cv_l, ev_l):
        return _combine_body(plan, cv_l, ev_l)
    return island
