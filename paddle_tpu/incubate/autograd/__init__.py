"""paddle.incubate.autograd: functional transforms + prim toggles.

Reference surface: python/paddle/incubate/autograd/ (vjp/jvp/Jacobian/Hessian
over primapi, enable_prim/disable_prim, forward_grad). The transforms
re-export paddle.autograd's jax-native versions; prim mode is inherently on
(every op IS a primitive jaxpr program), so the toggles track state for
API compatibility.
"""

from ...autograd import grad, hessian, jacobian, jvp, vjp  # noqa: F401

# reference incubate exposes capitalized lazy-evaluating classes; the jax-native
# implementations compute directly, so the names alias the functions
Jacobian = jacobian
Hessian = hessian

_prim_enabled = False


def enable_prim():
    global _prim_enabled
    _prim_enabled = True


def disable_prim():
    global _prim_enabled
    _prim_enabled = False


def prim_enabled() -> bool:
    return _prim_enabled


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode AD over captured static programs (reference
    primapi.forward_grad) is not supported; use
    paddle.incubate.autograd.jvp(func, xs, v) on a python function."""
    raise NotImplementedError(
        "forward_grad over captured static programs is not supported; use "
        "paddle.incubate.autograd.jvp(func, xs, v) on a python function"
    )
