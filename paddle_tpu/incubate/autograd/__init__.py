"""paddle.incubate.autograd: functional transforms + the prim/composite layer.

Reference surface: python/paddle/incubate/autograd/ (vjp/jvp/Jacobian/Hessian
over primapi, enable_prim/disable_prim, forward_grad — primapi.py:25) and the
composite-grad decomposition rules in paddle/fluid/prim/.

TPU re-design: every op already lowers to a jax-primitive composition, so
"prim mode" doesn't need a program rewriter. What it DOES change:

- fused custom_vjp kernels (Pallas flash attention, fused LN/RMSNorm) are
  only once-differentiable; with prim enabled the dispatch routes them to
  their primitive jnp compositions so arbitrary-order autodiff composes
  (the composite-grad role of fluid/prim — see nn/functional/_pallas_gate).
- `register_composite` lets users attach a decomposition for their own
  custom-vjp ops, consulted at the dispatch seam while prim is on.
- `forward_grad` records a forward-mode (jvp-of-replay) node into the
  captured static Program (static/program.forward_gradients).
"""

from ...autograd import grad, hessian, jacobian, jvp, vjp  # noqa: F401

# reference incubate exposes capitalized lazy-evaluating classes; the jax-native
# implementations compute directly, so the names alias the functions
Jacobian = jacobian
Hessian = hessian

_prim_enabled = False

# op_name -> pure composite fn (same signature as the op's pure lowering)
_composites = {}


def enable_prim():
    global _prim_enabled
    _prim_enabled = True


def disable_prim():
    global _prim_enabled
    _prim_enabled = False


def prim_enabled() -> bool:
    return _prim_enabled


def register_composite(op_name: str, fn=None):
    """Register a primitive decomposition for `op_name`, used by the op
    dispatch while prim is enabled (the composite-grad registration of
    fluid/prim). Usable as a decorator::

        @register_composite("my_fused_op")
        def my_composite(x, w): ...   # same signature as the pure lowering
    """
    if fn is None:
        def deco(f):
            _composites[op_name] = f
            return f

        return deco
    _composites[op_name] = fn
    return fn


def composite_for(op_name: str):
    """The registered decomposition for op_name iff prim mode is on."""
    if not _prim_enabled:
        return None
    return _composites.get(op_name)


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode AD over the captured static program (reference
    primapi.py:25 forward_grad): returns one grad var per output holding
    d(output)/d(inputs) . tangents, with tangents = grad_inputs (default
    ones). Must run under paddle.enable_static() with prim enabled, inside
    the program being built — like the reference."""
    if not _prim_enabled:
        raise RuntimeError(
            "forward_grad requires prim mode: call "
            "paddle.incubate.autograd.enable_prim() first (reference "
            "primapi.forward_grad has the same precondition)")
    from ...static.program import forward_gradients

    outs = forward_gradients(outputs, inputs, input_gradients=grad_inputs)
    return outs if isinstance(outputs, (list, tuple)) else outs[0]
