"""paddle.incubate.autotune surface (python/paddle/incubate/autotune.py:
set_config) over the kernel autotune cache (phi/kernels/autotune)."""

from ..kernels.autotune import (  # noqa: F401
    autotune_status,
    disable_autotune,
    enable_autotune,
    set_config,
)

__all__ = ["set_config"]
