"""Incubate optimizers: LookAhead and ModelAverage wrappers.

Reference surface: python/paddle/incubate/optimizer/ (lookahead.py,
modelaverage.py). Both wrap an inner optimizer and keep host-side slow/EMA
copies of the parameters.
"""

from __future__ import annotations

import jax.numpy as jnp


class LookAhead:
    """k steps forward, 1 step back (Zhang et al): every k inner steps, pull
    the fast weights toward the slow weights by alpha."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha, self.k = alpha, k
        self._step_num = 0
        # slow weights snapshot the INITIAL params (Zhang et al. / reference)
        self._slow = {id(p): p._value for p in (
            getattr(inner_optimizer, "_parameter_list", None)
            or getattr(inner_optimizer, "_parameters", None) or [])}

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)

    def _params(self):
        return (getattr(self.inner_optimizer, "_parameter_list", None)
                or getattr(self.inner_optimizer, "_parameters", None) or [])

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for p in self._params():
                slow = self._slow.setdefault(id(p), p._value)
                new_slow = slow + self.alpha * (p._value - slow)
                self._slow[id(p)] = new_slow
                p._set_value_raw(new_slow)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.inner_optimizer.clear_grad()
        return None, None

    def clear_grad(self, set_to_zero: bool = False):
        self.inner_optimizer.clear_grad(set_to_zero)


class ModelAverage:
    """Maintains a running average of parameters; apply()/restore() swap the
    averaged weights in for evaluation (reference incubate ModelAverage)."""

    def __init__(self, average_window_rate, parameters=None, min_average_window=10000,
                 max_average_window=10000, name=None):
        self.rate = average_window_rate
        self._parameters = list(parameters) if parameters is not None else []
        self._sum = {}
        self._count = 0
        self._backup = {}

    def step(self):
        self._count += 1
        for p in self._parameters:
            acc = self._sum.get(id(p))
            self._sum[id(p)] = p._value if acc is None else acc + p._value

    def update(self):
        self.step()

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._backup = {id(p): p._value for p in self._parameters}
            for p in self._parameters:
                if id(p) in self._sum and self._count:
                    p._set_value_raw((self._sum[id(p)] / self._count).astype(p._value.dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)

        return ctx()

    def restore(self, executor=None):
        for p in self._parameters:
            if id(p) in self._backup:
                p._set_value_raw(self._backup[id(p)])
        self._backup = {}
