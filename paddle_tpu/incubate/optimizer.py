"""Incubate optimizers: LookAhead and ModelAverage wrappers.

Reference surface: python/paddle/incubate/optimizer/ (lookahead.py,
modelaverage.py). Both wrap an inner optimizer and keep host-side slow/EMA
copies of the parameters.
"""

from __future__ import annotations

import jax.numpy as jnp


class LookAhead:
    """k steps forward, 1 step back (Zhang et al): every k inner steps, pull
    the fast weights toward the slow weights by alpha."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha, self.k = alpha, k
        self._step_num = 0
        # slow weights snapshot the INITIAL params (Zhang et al. / reference)
        self._slow = {id(p): p._value for p in (
            getattr(inner_optimizer, "_parameter_list", None)
            or getattr(inner_optimizer, "_parameters", None) or [])}

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)

    def _params(self):
        return (getattr(self.inner_optimizer, "_parameter_list", None)
                or getattr(self.inner_optimizer, "_parameters", None) or [])

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for p in self._params():
                slow = self._slow.setdefault(id(p), p._value)
                new_slow = slow + self.alpha * (p._value - slow)
                self._slow[id(p)] = new_slow
                p._set_value_raw(new_slow)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.inner_optimizer.clear_grad()
        return None, None

    def clear_grad(self, set_to_zero: bool = False):
        self.inner_optimizer.clear_grad(set_to_zero)


class ModelAverage:
    """Maintains a running average of parameters; apply()/restore() swap the
    averaged weights in for evaluation (reference incubate ModelAverage)."""

    def __init__(self, average_window_rate, parameters=None, min_average_window=10000,
                 max_average_window=10000, name=None):
        self.rate = average_window_rate
        self._parameters = list(parameters) if parameters is not None else []
        self._sum = {}
        self._count = 0
        self._backup = {}

    def step(self):
        self._count += 1
        for p in self._parameters:
            acc = self._sum.get(id(p))
            self._sum[id(p)] = p._value if acc is None else acc + p._value

    def update(self):
        self.step()

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._backup = {id(p): p._value for p in self._parameters}
            for p in self._parameters:
                if id(p) in self._sum and self._count:
                    p._set_value_raw((self._sum[id(p)] / self._count).astype(p._value.dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)

        return ctx()

    def restore(self, executor=None):
        for p in self._parameters:
            if id(p) in self._backup:
                p._set_value_raw(self._backup[id(p)])
        self._backup = {}


class LBFGS:
    """Limited-memory BFGS with optional strong-Wolfe line search
    (reference incubate/optimizer/lbfgs.py). Closure-driven like the
    reference: ``step(closure)`` re-evaluates the loss (the closure must
    zero grads, run forward, call backward) as many times as the line
    search needs.

    TPU note: L-BFGS is a host-driven sequential algorithm (curvature
    pairs, dot products, line search); the heavy work — the closure's
    forward/backward — still runs on device. History and direction math
    run on flattened f32 host vectors.
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        import numpy as np

        if parameters is None:
            raise ValueError("LBFGS requires parameters=")
        self._np = np
        self._parameters = list(parameters)
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        if weight_decay is not None or grad_clip is not None:
            raise NotImplementedError(
                "LBFGS does not apply weight_decay/grad_clip (matching its "
                "closure-driven contract); fold them into the closure's loss")
        self.line_search_fn = line_search_fn
        self._s: list = []  # param displacements
        self._y: list = []  # grad displacements

    # ---- flat <-> params ----
    def _gather(self, grads=False):
        np = self._np
        parts = []
        for p in self._parameters:
            if grads and p.grad is None:
                v = 0 * p._value  # parameter unused by the closure's loss
            else:
                v = p.grad._value if grads else p._value
            parts.append(np.asarray(v, np.float32).reshape(-1))
        return np.concatenate(parts) if parts else np.zeros(0, np.float32)

    def _scatter(self, flat):
        np = self._np
        i = 0
        for p in self._parameters:
            n = int(np.prod(p.shape)) if p.shape else 1
            block = flat[i:i + n].reshape(p.shape)
            p._set_value_raw(block.astype(str(p._value.dtype)))
            i += n

    def _direction(self, g):
        """Two-loop recursion over the (s, y) history."""
        np = self._np
        q = g.copy()
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / max(float(y @ s), 1e-20)
            a = rho * float(s @ q)
            alphas.append((a, rho, s, y))
            q -= a * y
        if self._y:
            s, y = self._s[-1], self._y[-1]
            q *= float(s @ y) / max(float(y @ y), 1e-20)
        for a, rho, s, y in reversed(alphas):
            b = rho * float(y @ q)
            q += (a - b) * s
        return -q

    def step(self, closure):
        np = self._np
        loss = closure()
        evals = 1
        for _ in range(self.max_iter):
            g = self._gather(grads=True)
            if np.max(np.abs(g), initial=0.0) <= self.tolerance_grad:
                break
            d = self._direction(g)
            x0 = self._gather()
            f0 = float(loss.numpy()) if hasattr(loss, "numpy") else float(loss)
            gtd = float(g @ d)
            if gtd > -1e-20:  # not a descent direction: reset history
                self._s, self._y = [], []
                d = -g
                gtd = float(g @ d)
            t = self.learning_rate

            def evaluate(step_size):
                self._scatter(x0 + step_size * d)
                l = closure()
                return (float(l.numpy()) if hasattr(l, "numpy") else float(l),
                        self._gather(grads=True), l)

            if self.line_search_fn == "strong_wolfe":
                c1, c2 = 1e-4, 0.9
                lo, hi = 0.0, None
                best = None
                for _ls in range(10):
                    f_t, g_t, loss_t = evaluate(t)
                    evals += 1
                    if f_t > f0 + c1 * t * gtd:
                        hi = t
                        t = (lo + hi) / 2
                    elif abs(float(g_t @ d)) > c2 * abs(gtd):
                        lo = t
                        t = 2 * t if hi is None else (lo + hi) / 2
                    else:
                        best = (f_t, g_t, loss_t)
                        break
                    if evals >= self.max_eval:
                        break
                if best is None and evals < self.max_eval:
                    f_t, g_t, loss_t = evaluate(t)
                    evals += 1
                f_t, g_new, loss = best if best else (f_t, g_t, loss_t)
            else:
                self._scatter(x0 + t * d)
                loss = closure()
                evals += 1
                g_new = self._gather(grads=True)
            x_new = self._gather()
            s = x_new - x0
            y = g_new - g
            if float(y @ s) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)
            if np.max(np.abs(s), initial=0.0) <= self.tolerance_change:
                break
            if evals >= self.max_eval:
                break
        return loss

    def clear_grad(self):
        for p in self._parameters:
            p.clear_gradient()

    def state_dict(self):
        return {"s": [v.copy() for v in self._s],
                "y": [v.copy() for v in self._y]}

    def set_state_dict(self, state):
        self._s = [self._np.asarray(v) for v in state.get("s", [])]
        self._y = [self._np.asarray(v) for v in state.get("y", [])]
