"""Incubate op surface: fused softmax-mask, legacy graph-op names, identity_loss.

Reference surface: python/paddle/incubate/__init__.py — graph_send_recv etc.
pre-date the paddle.geometric package; they alias the geometric ops here.
softmax_mask_fuse maps to a single fused jnp chain (XLA fuses it into one
kernel — the point of the reference's fused CUDA op).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..geometric.math import segment_max, segment_mean, segment_min, segment_sum  # noqa: F401
from ..geometric.reindex import reindex_graph as graph_reindex  # noqa: F401
from ..geometric.sampling import sample_neighbors as graph_sample_neighbors  # noqa: F401
from ..ops._dispatch import apply, as_tensor


def graph_send_recv(x, src_index, dst_index, reduce_op="sum", out_size=None, name=None):
    """Legacy name for geometric.send_u_recv (reference incubate alias)."""
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=reduce_op, out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes, sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (reference graph_khop_sampler): repeated
    one-hop sampling with reindexing, host-side (data-prep op). Returns
    (edge_src, edge_dst, sample_index, reindex_nodes) like the reference —
    sample_index maps local ids back to global node ids, reindex_nodes are
    the local ids of the input center nodes."""
    import numpy as np

    from ..core.tensor import Tensor
    from ..geometric.sampling import sample_neighbors

    if return_eids:
        raise NotImplementedError("return_eids=True is not supported yet")
    cur = input_nodes
    edge_src_list, edge_dst_list = [], []
    input_np = np.asarray(as_tensor(input_nodes)._value)
    all_nodes = [input_np]
    for size in sample_sizes:
        out_neighbors, out_count = sample_neighbors(row, colptr, cur, sample_size=size)
        nv = np.asarray(as_tensor(out_neighbors)._value)
        cv = np.asarray(as_tensor(out_count)._value)
        dst = np.repeat(np.asarray(as_tensor(cur)._value), cv)
        edge_src_list.append(nv)
        edge_dst_list.append(dst)
        all_nodes.append(nv)
        cur = Tensor(jnp.asarray(np.unique(nv)))
    nodes = np.concatenate(all_nodes)
    uniq, first = np.unique(nodes, return_index=True)
    order = np.argsort(first, kind="stable")
    sample_index = uniq[order]  # local id -> global node id
    remap = {int(v): i for i, v in enumerate(sample_index)}
    src = np.asarray([remap[int(v)] for v in np.concatenate(edge_src_list)], np.int64)
    dst = np.asarray([remap[int(v)] for v in np.concatenate(edge_dst_list)], np.int64)
    reindex_nodes = np.asarray([remap[int(v)] for v in input_np], np.int64)
    return (Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(sample_index)), Tensor(jnp.asarray(reindex_nodes)))


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fused chain (reference fused_softmax_mask op:
    incubate/operators/softmax_mask_fuse.py)."""
    x, mask = as_tensor(x), as_tensor(mask)

    def f(xv, mv):
        return jax.nn.softmax(xv.astype(jnp.float32) + mv.astype(jnp.float32), -1).astype(xv.dtype)

    return apply("softmax_mask_fuse", f, x, mask)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with causal (upper-triangle) mask fused (reference
    fused_softmax_mask_upper_triangle): rows attend to positions <= row."""
    x = as_tensor(x)

    def f(xv):
        s = xv.shape[-1]
        causal = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(causal, xv.astype(jnp.float32), -1e30)
        return jax.nn.softmax(scores, -1).astype(xv.dtype)

    return apply("softmax_mask_fuse_upper_triangle", f, x)


def identity_loss(x, reduction="none"):
    """Mark a tensor as a loss without changing it (reference identity_loss op,
    IPU heritage); reduction in {none, sum, mean} applies on the way out."""
    x = as_tensor(x)
    if reduction in (0, "sum"):
        from ..ops.math import sum as _sum

        return _sum(x)
    if reduction in (1, "mean"):
        from ..ops.math import mean as _mean

        return _mean(x)
    return x
