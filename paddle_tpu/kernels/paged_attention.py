"""Ragged paged-decode attention, Pallas TPU (vLLM PagedAttention analog).

One decode step attends each slot's single query token against that slot's
live KV pages only. The pools are ``[num_pages, H_kv, page_size, D]`` (one
per layer); routing is a ``[B, num_blocks]`` int32 page table whose entries
are pool page ids (``-1`` sentinel pads unallocated blocks). Both the table
and the per-slot positions ride as SCALAR-PREFETCH operands
(``PrefetchScalarGridSpec``), so the grid's K/V ``index_map`` can gather the
b-th slot's i-th page directly out of the pool — the kernel never touches a
dense ``[B, S_max]`` view, and pages of finished requests are simply never
fetched.

Grid is ``(B, num_blocks)`` with the block dim sequential: per slot a
flash-style online softmax (exp2 domain, f32 stats in VMEM scratch —
same scheme as flash_attention.py) streams the live pages, skipping blocks
past ``positions[b] // page_size`` entirely and masking the tail of the
last live page with ``token_pos <= positions[b]``. Sentinel entries clamp
to page 0 — a reserved trash page the allocator never hands out — so the
gather stays in-bounds for empty slots and the mask keeps the math right.

GQA runs as a static per-KV-head-group loop: each group is a
``[rep, D] x [D, page]`` dot, so K/V are read once per group instead of
being materialized at query-head width.

Numerics mirror ``serving.kv_cache.decode_attend`` (the oracle): q
pre-scaled in its own dtype, f32 scores/softmax, output cast to v's dtype —
parity is asserted across ragged batches by tests/test_paged_kv.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .flash_attention import (LANES, LOG2E, NEG_INF, _compiler_params,
                              _interpret)


def _decode_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, num_blocks: int, page_size: int,
                   num_kv_heads: int, rep: int):
    """Grid (B, num_blocks): pages STREAM through the trailing (sequential)
    dim; running (max, sum, acc) live in VMEM scratch across page
    iterations and the epilogue normalizes on the last block. Blocks at or
    past the slot's live count contribute nothing and are skipped whole."""
    b = pl.program_id(0)
    i = pl.program_id(1)
    pos = pos_ref[b]
    # pages [0, pos // page_size] hold written tokens (position pos is
    # written before the attend — see paged_write_kv)
    live_hi = pos // jnp.int32(page_size) + 1

    @pl.when(i == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(i < live_hi)
    def _compute():
        q = q_ref[0]  # [Hq, D], pre-scaled by 1/sqrt(D) in q's dtype
        k = k_ref[0]  # [Hkv, page_size, D]
        v = v_ref[0]
        # GQA: one [rep, D] x [D, page] dot per KV-head group — K is read
        # at its stored width, never expanded to Hq
        s_groups = [
            jax.lax.dot_general(
                q[g * rep:(g + 1) * rep], k[g], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            for g in range(num_kv_heads)
        ]
        s = jnp.concatenate(s_groups, axis=0) * jnp.float32(LOG2E)
        Hq = s.shape[0]
        tok = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (Hq, page_size), 1)
        s = jnp.where(tok <= pos, s, NEG_INF)  # [Hq, page_size], log2-domain
        m = m_scr[:, 0]
        l = l_scr[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.concatenate([
            jax.lax.dot_general(
                p[g * rep:(g + 1) * rep].astype(v.dtype), v[g],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            for g in range(num_kv_heads)
        ], axis=0)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + pv
        m_scr[...] = jax.lax.broadcast_in_dim(m_new, m_scr.shape, (0,))
        l_scr[...] = jax.lax.broadcast_in_dim(l_new, l_scr.shape, (0,))

    @pl.when(i == num_blocks - 1)
    def _epilogue():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, page_table, positions,
                    interpret: bool = None):
    """Ragged paged-decode attention over block-paged KV pools.

    q            ``[B, H_q, 1, D]`` — one query token per slot
    k/v_pool     ``[P, H_kv, page_size, D]`` — this layer's page pools
    page_table   ``[B, num_blocks]`` int32 pool page ids (-1 = unallocated)
    positions    ``[B]`` int32 — each slot's current token index

    Returns ``[B, H_q, 1, D]`` in v's dtype — drop-in for
    ``decode_attend(q, dense_k, dense_v, positions)`` when the dense caches
    hold the same bytes the table maps (tests pin this parity).
    """
    from jax.experimental.pallas import tpu as pltpu

    B, Hq, T, D = q.shape
    if T != 1:
        raise ValueError(f"paged_attention decodes one token per slot, got T={T}")
    P, Hkv, page_size, _ = k_pool.shape
    num_blocks = page_table.shape[1]
    rep = Hq // Hkv
    qs = (q[:, :, 0, :] * jnp.asarray(1.0 / np.sqrt(D), q.dtype))  # [B, Hq, D]
    table = page_table.astype(jnp.int32)
    pos = jnp.asarray(positions, jnp.int32)
    if pos.ndim == 0:
        pos = jnp.broadcast_to(pos, (B,))

    def _page_map(b, i, tbl, _pos):
        # sentinel entries clamp to the reserved trash page so the fetch
        # stays in-bounds; the live_hi bound keeps them out of the math
        return (jnp.maximum(tbl[b, i], 0), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, num_blocks),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, i, tbl, _pos: (b, 0, 0)),
            pl.BlockSpec((1, Hkv, page_size, D), _page_map),
            pl.BlockSpec((1, Hkv, page_size, D), _page_map),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, i, tbl, _pos: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hq, LANES), jnp.float32),
            pltpu.VMEM((Hq, LANES), jnp.float32),
            pltpu.VMEM((Hq, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, num_blocks=num_blocks,
                          page_size=page_size, num_kv_heads=Hkv, rep=rep),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), v_pool.dtype),
        compiler_params=_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret() if interpret is None else interpret,
    )(table, pos, qs, k_pool, v_pool)
    return out[:, :, None, :]
