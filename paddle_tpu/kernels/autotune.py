"""Runtime kernel autotuning (phi/kernels/autotune: cache.h AlgorithmsCache,
auto_tune_base.h AutoTuneBase::PickBestAlgorithm, switch_autotune.cc).

Reference behavior: the first executions of a tunable op time every candidate
algorithm (cuDNN conv algos, transpose tilings), cache the winner keyed by the
op's shape/dtype signature, and later executions hit the cache. TPU re-design:
the tunables are Pallas grid/block configurations (block_q/block_k for flash
attention, tile sizes for norms) — XLA owns everything else. The cache
persists as JSON (~/.cache/paddle_tpu/autotune.json) so tuning cost is paid
once per machine, mirroring the reference's process-lifetime cache but
surviving restarts (compile times on TPU make re-tuning much more expensive
than re-running a cuDNN search).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "AutoTuneCache", "enable_autotune", "disable_autotune", "set_config",
    "autotune_status", "pick_best",
]

_state = {
    "enabled": False,
    "measure_repeats": 3,
    "persist": True,
}
_lock = threading.RLock()


def _cache_path() -> str:
    base = os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE")
    if base:
        return base
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "autotune.json")


class AutoTuneCache:
    """(kernel, signature) -> winning config, with hit/miss stats
    (cache.h AlgorithmsCache + autotune_status analog)."""

    def __init__(self):
        self._data: Dict[str, Dict[str, Any]] = {}
        self._hits = 0
        self._misses = 0
        self._loaded = False

    def _ensure_loaded(self):
        if self._loaded:
            return
        self._loaded = True
        path = _cache_path()
        try:
            with open(path) as f:
                disk = json.load(f)
            if isinstance(disk, dict):
                for k, v in disk.items():
                    if isinstance(v, dict):  # tolerate corrupt/old entries
                        self._data.setdefault(k, {}).update(v)
        except (OSError, ValueError):
            pass

    def get(self, kernel: str, key: str):
        with _lock:
            self._ensure_loaded()
            got = self._data.get(kernel, {}).get(key)
            if got is None:
                self._misses += 1
            else:
                self._hits += 1
            return got

    def put(self, kernel: str, key: str, config):
        with _lock:
            self._ensure_loaded()
            self._data.setdefault(kernel, {})[key] = config
            if _state["persist"]:
                self._save()

    def _save(self):
        path = _cache_path()
        try:
            # merge under what's on disk (ours wins) so clear() + put() can
            # never wipe configs tuned by other processes/sessions
            merged: Dict[str, Dict[str, Any]] = {}
            try:
                with open(path) as f:
                    disk = json.load(f)
                if isinstance(disk, dict):
                    merged.update({k: dict(v) for k, v in disk.items()
                                   if isinstance(v, dict)})
            except (OSError, ValueError):
                pass
            for k, v in self._data.items():
                merged.setdefault(k, {}).update(v)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(merged, f)
            os.replace(tmp, path)
        except OSError:
            pass  # cache is best-effort

    def clear(self):
        with _lock:
            self._data.clear()
            self._hits = self._misses = 0
            self._loaded = True  # don't resurrect from disk

    def size(self) -> int:
        with _lock:
            return sum(len(v) for v in self._data.values())

    def stats(self) -> Dict[str, float]:
        with _lock:
            total = self._hits + self._misses
            return {"hits": self._hits, "misses": self._misses,
                    "hit_rate": self._hits / total if total else 0.0,
                    "size": self.size()}


cache = AutoTuneCache()


def enable_autotune():
    _state["enabled"] = True


def disable_autotune():
    _state["enabled"] = False


def set_config(config: Optional[dict] = None):
    """paddle.incubate.autotune.set_config contract: {"kernel": {"enable":
    bool, ...}}; unknown sections are ignored (dataloader/layout tuning have
    no TPU meaning — XLA owns layout)."""
    if config is None:
        _state["enabled"] = True
        return
    if isinstance(config, str):  # reference contract: path to a JSON file
        with open(config) as f:
            config = json.load(f)
    kernel_cfg = config.get("kernel", {})
    if "enable" in kernel_cfg:
        _state["enabled"] = bool(kernel_cfg["enable"])
    if "repeats" in kernel_cfg:
        _state["measure_repeats"] = max(1, int(kernel_cfg["repeats"]))
    if "persist" in kernel_cfg:
        _state["persist"] = bool(kernel_cfg["persist"])


def autotune_status() -> Dict[str, Any]:
    s = dict(cache.stats())
    s["enabled"] = _state["enabled"]
    return s


def enabled() -> bool:
    return _state["enabled"]


def _measure(fn: Callable[[], Any]) -> float:
    """Median wall time of fn() with device sync (PickBestAlgorithm timing).

    Sync is a host transfer of one element of the output, NOT
    block_until_ready: on remote-tunnel PJRT backends (axon)
    block_until_ready acks dispatch, not completion, so every candidate
    would time as ~dispatch latency and the "winner" would be noise.
    A device->host copy of a single scalar is the only reliable barrier.
    """
    import jax
    import jax.numpy as jnp

    def sync(out):
        import numpy as np
        # All leaves of one call complete together, so one one-element
        # device->host copy of the last leaf is a sufficient barrier.
        # A failed transfer must propagate (pick_best disqualifies the
        # candidate) — falling back to block_until_ready would time noise.
        leaves = [x for x in jax.tree_util.tree_leaves(out)
                  if hasattr(x, "dtype")]
        if leaves:
            np.asarray(jnp.ravel(leaves[-1])[:1])

    sync(fn())  # warmup (compile)
    times = []
    for _ in range(_state["measure_repeats"]):
        t0 = time.perf_counter()
        sync(fn())
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def pick_best(kernel: str, key: Sequence, candidates: List,
              make_run: Callable[[Any], Callable[[], Any]],
              default=None):
    """Return the best config for (kernel, key).

    - cache hit -> cached winner
    - autotune disabled -> ``default`` (heuristic path, no measurement)
    - else time every candidate via ``make_run(config)() -> output`` and
      cache the fastest (exceptions disqualify a candidate).
    """
    skey = json.dumps(list(key))
    hit = cache.get(kernel, skey)
    if hit is not None:
        return tuple(hit) if isinstance(hit, list) else hit
    if not _state["enabled"] or not candidates:
        return default if default is not None else (candidates[0] if candidates else None)
    best, best_t = None, float("inf")
    for cand in candidates:
        try:
            t = _measure(make_run(cand))
        except Exception:
            continue
        if t < best_t:
            best, best_t = cand, t
    if best is None:
        return default
    cache.put(kernel, skey, best)
    return best
