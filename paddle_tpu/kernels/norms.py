"""Fused LayerNorm / RMSNorm Pallas kernels (phi/kernels/gpu/layer_norm_kernel.cu
and rms_norm fusion analogs): one HBM pass computes stats + normalizes +
applies affine. Backward recomputes stats from the saved input — on TPU the
stat recompute fuses into the dx elementwise pipeline, which is cheaper than
materializing (mean, rstd) through HBM with Mosaic's (8, 128)-tile layout."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _ln_kernel(x_ref, w_ref, b_ref, y_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)  # [rows, H]
    mean = jnp.mean(x, axis=-1)
    var = jnp.mean(jnp.square(x - mean[:, None]), axis=-1)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean[:, None]) * rstd[:, None] * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)


def _rms_kernel(x_ref, w_ref, y_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1) + eps)
    y_ref[:] = (x * rstd[:, None] * w_ref[:].astype(jnp.float32)).astype(y_ref.dtype)


def _rows_block(n_rows: int) -> int:
    """Mosaic tiling: the rows block must be a multiple of 8 or span all rows.
    Non-dividing blocks are fine (pl.cdiv grid pads the tail; padded rows are
    row-independent garbage the out-of-bounds write discards)."""
    if n_rows <= 256:
        return n_rows
    for b in (256, 128, 64, 32, 16, 8):
        if n_rows % b == 0:
            return b
    return 8  # non-dividing: grid pads the tail block


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, weight, bias, eps: float = 1e-5):
    return _ln_fwd(x, weight, bias, eps)[0]


def _ln_fwd(x, weight, bias, eps):
    orig_shape = x.shape
    H = orig_shape[-1]
    x2 = x.reshape(-1, H)
    R = x2.shape[0]
    br = min(_rows_block(R), R)
    y = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(pl.cdiv(R, br),),
        in_specs=[
            pl.BlockSpec((br, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
            pl.BlockSpec((H,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, H), x.dtype),
        interpret=_interpret(),
    )(x2, weight, bias)
    return y.reshape(orig_shape), (x2, weight, orig_shape)


def _ln_fwd_rule(x, weight, bias, eps):
    y, res = _ln_fwd(x, weight, bias, eps)
    return y, res


def _ln_bwd_rule(eps, res, g):
    x2, weight, orig_shape = res
    H = x2.shape[1]
    g2 = g.reshape(-1, H).astype(jnp.float32)
    xf = x2.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True) + eps)
    xhat = (xf - mean) * rstd
    wg = g2 * weight.astype(jnp.float32)
    dx = (
        wg - jnp.mean(wg, axis=-1, keepdims=True) - xhat * jnp.mean(wg * xhat, axis=-1, keepdims=True)
    ) * rstd
    dw = jnp.sum(g2 * xhat, axis=0)
    db = jnp.sum(g2, axis=0)
    return dx.reshape(orig_shape).astype(x2.dtype), dw.astype(weight.dtype), db.astype(weight.dtype)


fused_layer_norm.defvjp(_ln_fwd_rule, _ln_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_rms_norm(x, weight, eps: float = 1e-6):
    return _rms_fwd(x, weight, eps)[0]


def _rms_fwd(x, weight, eps):
    orig_shape = x.shape
    H = orig_shape[-1]
    x2 = x.reshape(-1, H)
    R = x2.shape[0]
    br = min(_rows_block(R), R)
    y = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(pl.cdiv(R, br),),
        in_specs=[
            pl.BlockSpec((br, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, H), x.dtype),
        interpret=_interpret(),
    )(x2, weight)
    return y.reshape(orig_shape), (x2, weight, orig_shape)


def _rms_fwd_rule(x, weight, eps):
    y, res = _rms_fwd(x, weight, eps)
    return y, res


def _rms_bwd_rule(eps, res, g):
    x2, weight, orig_shape = res
    H = x2.shape[1]
    g2 = g.reshape(-1, H).astype(jnp.float32)
    xf = x2.astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
    xhat = xf * rstd
    wg = g2 * weight.astype(jnp.float32)
    dx = (wg - xhat * jnp.mean(wg * xhat, axis=-1, keepdims=True)) * rstd
    dw = jnp.sum(g2 * xhat, axis=0)
    return dx.reshape(orig_shape).astype(x2.dtype), dw.astype(weight.dtype)


fused_rms_norm.defvjp(_rms_fwd_rule, _rms_bwd_rule)
