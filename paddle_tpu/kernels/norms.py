"""Fused LayerNorm / RMSNorm Pallas kernels (phi/kernels/gpu/layer_norm_kernel.cu
and rms_norm fusion analogs): one HBM pass computes stats + normalizes +
applies affine; backward recomputes from saved (mean, rstd)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _ln_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)  # [rows, H]
    mean = jnp.mean(x, axis=-1)
    var = jnp.mean(jnp.square(x - mean[:, None]), axis=-1)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean[:, None]) * rstd[:, None] * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _rms_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1) + eps)
    y_ref[:] = (x * rstd[:, None] * w_ref[:].astype(jnp.float32)).astype(y_ref.dtype)
    rstd_ref[:] = rstd


def _rows_block(n_rows: int) -> int:
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n_rows % b == 0:
            return b
    return 1


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, weight, bias, eps: float = 1e-5):
    return _ln_fwd(x, weight, bias, eps)[0]


def _ln_fwd(x, weight, bias, eps):
    orig_shape = x.shape
    H = orig_shape[-1]
    x2 = x.reshape(-1, H)
    R = x2.shape[0]
    br = _rows_block(R)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(R // br,),
        in_specs=[
            pl.BlockSpec((br, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
            pl.BlockSpec((H,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((br, H), lambda i: (i, 0)),
            pl.BlockSpec((br,), lambda i: (i,)),
            pl.BlockSpec((br,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, H), x.dtype),
            jax.ShapeDtypeStruct((R,), jnp.float32),
            jax.ShapeDtypeStruct((R,), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, weight, bias)
    return y.reshape(orig_shape), (x2, weight, mean, rstd, orig_shape)


def _ln_fwd_rule(x, weight, bias, eps):
    y, res = _ln_fwd(x, weight, bias, eps)
    return y, res


def _ln_bwd_rule(eps, res, g):
    x2, weight, mean, rstd, orig_shape = res
    H = x2.shape[1]
    g2 = g.reshape(-1, H).astype(jnp.float32)
    xf = x2.astype(jnp.float32)
    xhat = (xf - mean[:, None]) * rstd[:, None]
    wg = g2 * weight.astype(jnp.float32)
    dx = (
        wg - jnp.mean(wg, axis=-1, keepdims=True) - xhat * jnp.mean(wg * xhat, axis=-1, keepdims=True)
    ) * rstd[:, None]
    dw = jnp.sum(g2 * xhat, axis=0)
    db = jnp.sum(g2, axis=0)
    return dx.reshape(orig_shape).astype(x2.dtype), dw.astype(weight.dtype), db.astype(weight.dtype)


fused_layer_norm.defvjp(_ln_fwd_rule, _ln_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_rms_norm(x, weight, eps: float = 1e-6):
    return _rms_fwd(x, weight, eps)[0]


def _rms_fwd(x, weight, eps):
    orig_shape = x.shape
    H = orig_shape[-1]
    x2 = x.reshape(-1, H)
    R = x2.shape[0]
    br = _rows_block(R)
    y, rstd = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(R // br,),
        in_specs=[
            pl.BlockSpec((br, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((br, H), lambda i: (i, 0)),
            pl.BlockSpec((br,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, H), x.dtype),
            jax.ShapeDtypeStruct((R,), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, weight)
    return y.reshape(orig_shape), (x2, weight, rstd, orig_shape)


def _rms_fwd_rule(x, weight, eps):
    y, res = _rms_fwd(x, weight, eps)
    return y, res


def _rms_bwd_rule(eps, res, g):
    x2, weight, rstd, orig_shape = res
    H = x2.shape[1]
    g2 = g.reshape(-1, H).astype(jnp.float32)
    xf = x2.astype(jnp.float32)
    xhat = xf * rstd[:, None]
    wg = g2 * weight.astype(jnp.float32)
    dx = (wg - xhat * jnp.mean(wg * xhat, axis=-1, keepdims=True)) * rstd[:, None]
    dw = jnp.sum(g2 * xhat, axis=0)
    return dx.reshape(orig_shape).astype(x2.dtype), dw.astype(weight.dtype)


fused_rms_norm.defvjp(_rms_fwd_rule, _rms_bwd_rule)
