"""Shared raw-jnp elementwise helpers for fused paths."""

from __future__ import annotations


def tanh_gelu_raw(x):
    """Dtype-preserving tanh-approximation GELU on a raw jnp array:
    0.5*x*(1+tanh(sqrt(2/pi)*(x+0.044715*x^3))) with python-scalar
    (weak-typed) constants so bf16 stays bf16 end to end — jax.nn.gelu
    upcasts bf16 internally, which measured 20% SLOWER than this chain.
    Single definition shared by GeluFusePass, FcFusePass, and the chunked
    masked-LM head so the fused paths cannot drift numerically."""
    import jax.numpy as jnp

    inner = x + 0.044715 * x * x * x
    return 0.5 * x * (1.0 + jnp.tanh(0.7978845608028654 * inner))
