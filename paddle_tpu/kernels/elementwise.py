"""Shared raw-jnp elementwise helpers for fused paths."""

from __future__ import annotations


def layer_norm_raw(x, g, b, eps):
    """Plain-jnp layer norm over the last axis on raw arrays: f32 stats,
    output in x's dtype, affine params applied flattened. The XLA-fusable
    reference the recomposition passes and the chunked LM head bind —
    deliberately NOT the Pallas kernel: at serving shapes the kernel is
    only at per-op parity and its call boundary blocks XLA from fusing the
    surrounding residual adds (measured x0.81 end-to-end when every LN of
    a BERT trace was rebound to Pallas)."""
    import jax
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = jnp.square(xf - mu).mean(-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return y.astype(x.dtype) * g.reshape(-1) + b.reshape(-1)


def tanh_gelu_raw(x):
    """Dtype-preserving tanh-approximation GELU on a raw jnp array:
    0.5*x*(1+tanh(sqrt(2/pi)*(x+0.044715*x^3))) with python-scalar
    (weak-typed) constants so bf16 stays bf16 end to end — jax.nn.gelu
    upcasts bf16 internally, which measured 20% SLOWER than this chain.
    Single definition shared by GeluFusePass, FcFusePass, and the chunked
    masked-LM head so the fused paths cannot drift numerically."""
    import jax.numpy as jnp

    inner = x + 0.044715 * x * x * x
    return 0.5 * x * (1.0 + jnp.tanh(0.7978845608028654 * inner))
