"""Pallas TPU kernels — the CUDA-kernel-family replacement (SURVEY §2.2).

Where the reference hand-writes CUDA (flash_attn_kernel.cu, fused_adam,
fused layer_norm in phi/kernels/gpu + fusion/), the TPU build hand-writes
Pallas/Mosaic. Every kernel here:
- computes in f32 on the MXU/VPU regardless of storage dtype,
- has a jnp fallback + interpret mode so tests run on CPU,
- is wired behind the op-registry variant seam (ops use it when
  FLAGS_use_pallas_kernels and the backend is TPU).
"""

from .flash_attention import flash_attention_fwd  # noqa: F401
from .paged_attention import paged_attention  # noqa: F401
from .norms import fused_layer_norm, fused_rms_norm  # noqa: F401
from .fused_optim import fused_adamw_update  # noqa: F401
from .quant import (dequantize_block_scaled,  # noqa: F401
                    fit_block_size, quantize_block_scaled)
