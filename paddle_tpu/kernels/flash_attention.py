"""Flash attention, Pallas TPU (phi/kernels/gpu/flash_attn_kernel.cu analog).

Blockwise-softmax attention with O(S) memory: forward keeps running
(max, sum, acc) per query block while streaming key blocks through VMEM;
backward is the standard two-kernel split (dq; dk+dv) recomputing P from the
saved logsumexp. Layout is paddle's flash layout [B, S, H, D]; heads fold
into the grid's leading axis so each program owns one (batch, head) pair and
the MXU sees [block_q, D] x [D, block_k] tiles.

Causal masking skips fully-masked key blocks via the loop bound (not just a
mask), halving causal FLOPs — same trick as the CUDA kernel's early exit.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
LOG2E = 1.4426950408889634  # softmax runs in the exp2 domain (see _fwd_kernel)
# TPU vector lanes: scalar-per-row outputs (lse, delta) are broadcast across a
# 128-wide trailing dim so their blocks satisfy Mosaic's (8, 128) tiling rule —
# same layout as jax.experimental.pallas.ops.tpu.flash_attention (MIN_BLOCK_SIZE).
LANES = 128


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _compiler_params(pltpu, **kw):
    """jax 0.4.x ships the params class as ``TPUCompilerParams``; newer
    releases renamed it ``CompilerParams``. Accept either spelling."""
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


# ---------------- forward ----------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, num_kb: int, block_q: int, block_k: int, causal: bool, scale: float):
    """Grid (BH, num_q, num_k): K/V blocks STREAM through the trailing
    (sequential) grid dim, so VMEM holds only [block] tiles — never full-S
    K/V. Running (max, sum, acc) live in VMEM scratch across k iterations;
    the epilogue writes o/lse on the last relevant k block."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    bq, d = q_ref.shape[1], q_ref.shape[2]
    # causal: key blocks strictly after the diagonal contribute nothing
    kb_hi = ((qi + 1) * bq + jnp.int32(block_k - 1)) // jnp.int32(block_k) if causal else num_kb

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(ki < kb_hi)
    def _compute():
        # MXU dots take the native (bf16) operands — fp32 inputs run the MXU
        # at a fraction of peak; fp32 lives only in accumulators/stats
        # (preferred_element_type pins the accumulation dtype). Softmax runs
        # in the exp2 domain: log2(e) folds into the dot's scale, saving a
        # full [bq, bk] multiply pass per block (stats/lse stay log2-domain;
        # the bwd kernels use the same domain).
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * jnp.float32(scale * LOG2E)  # [bq, bk], log2-domain
        if causal:
            qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m = m_scr[:, 0]
        l = l_scr[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jax.lax.broadcast_in_dim(m_new, m_scr.shape, (0,))
        l_scr[...] = jax.lax.broadcast_in_dim(l_new, l_scr.shape, (0,))

    @pl.when(ki == num_kb - 1)
    def _epilogue():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = jax.lax.broadcast_in_dim(
            m_scr[:, 0] + jnp.log2(l_safe), (bq, LANES), (0,))


def _fwd(q, k, v, causal: bool, scale: float, block_q: int, block_k: int):
    B, S, H, D = q.shape
    qt = jnp.swapaxes(q, 1, 2).reshape(B * H, S, D)
    kt = jnp.swapaxes(k, 1, 2).reshape(B * H, S, D)
    vt = jnp.swapaxes(v, 1, 2).reshape(B * H, S, D)
    num_kb = S // block_k
    grid = (B * H, S // block_q, num_kb)
    from jax.experimental.pallas import tpu as pltpu

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, num_kb=num_kb, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, S, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            # the 2048x1024 fp32 score tile + bf16 p + double-buffered K/V
            # brush past the 16 MiB default scoped-vmem cap; v5e has 128 MiB
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_interpret(),
    )(qt, kt, vt)
    return o, lse[..., 0], (qt, kt, vt)


# ---------------- backward ----------------
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
               *, num_kb, block_k, causal, scale):
    """Grid (BH, num_q, num_k): K/V stream through the trailing dim, dq
    accumulates in VMEM scratch."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    bq, d = q_ref.shape[1], q_ref.shape[2]
    kb_hi = ((qi + 1) * bq + jnp.int32(block_k - 1)) // jnp.int32(block_k) if causal else num_kb

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(ki < kb_hi)
    def _compute():
        # native-dtype MXU operands + log2-domain p — see _fwd_kernel
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]  # [bq, 1] (lanes-broadcast layout), log2-domain
        delta = delta_ref[0][:, :1]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * jnp.float32(scale * LOG2E)
        if causal:
            qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp2(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * jnp.float32(scale)).astype(k.dtype)
        dq_scr[...] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == num_kb - 1)
    def _epilogue():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                dk_scr, dv_scr, *, num_qb, block_q, causal, scale):
    """Grid (BH, num_k, num_q): Q/dO stream through the trailing dim, dk/dv
    accumulate in VMEM scratch."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    bk, d = k_ref.shape[1], k_ref.shape[2]
    # causal: query blocks before this key block contribute nothing
    qb_lo = (ki * bk) // block_q if causal else 0

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(qi >= qb_lo)
    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]  # [bq, 1], log2-domain
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * jnp.float32(scale * LOG2E)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            kpos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp2(s - lse)  # [bq, bk]
        dv_scr[...] += jax.lax.dot_general(p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * jnp.float32(scale)).astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _epilogue():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd(causal, scale, block_q, block_k, res, g):
    from jax.experimental.pallas import tpu as pltpu

    qt, kt, vt, o, lse = res
    BH, S, D = qt.shape
    do = jnp.swapaxes(g, 1, 2).reshape(BH, S, D)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [BH, S]
    # lanes-broadcast layout for the per-row scalars (see LANES above)
    lse = jnp.broadcast_to(lse[..., None], (BH, S, LANES))
    delta = jnp.broadcast_to(delta[..., None], (BH, S, LANES))
    num_kb = S // block_k
    num_qb = S // block_q
    seq_par = ("parallel", "parallel", "arbitrary")

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, num_kb=num_kb, block_k=block_k, causal=causal, scale=scale),
        grid=(BH, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), qt.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_compiler_params(pltpu, dimension_semantics=seq_par),
        interpret=_interpret(),
    )(qt, kt, vt, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, num_qb=num_qb, block_q=block_q, causal=causal, scale=scale),
        grid=(BH, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), kt.dtype),
            jax.ShapeDtypeStruct((BH, S, D), vt.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_compiler_params(pltpu, dimension_semantics=seq_par),
        interpret=_interpret(),
    )(qt, kt, vt, do, lse, delta)

    def unfold(x, B, H):
        return jnp.swapaxes(x.reshape(B, H, S, D), 1, 2)

    B = g.shape[0]
    H = g.shape[2]
    return unfold(dq, B, H), unfold(dk, B, H), unfold(dv, B, H)


def _pick_blocks(S: int, role: str = "fwd"):
    # measured on v5e (D=128): bigger blocks win — fewer grid steps amortize
    # the per-block epilogue. S=1024: (1024,1024) beats (512,512) by ~29%;
    # S=4096: fwd (2048,1024) beats (1024,1024) by ~18% (the fp32 score
    # tile 2048x1024x4B = 8 MiB still fits VMEM). The BACKWARD kernels hold
    # two score-sized tiles (p and the ds/dp chain), so bq caps at 1024
    # there — fwd/bwd block choices are independent (residuals are full
    # [BH, S, D] arrays; only the block-free lse layout is shared).
    bq_cap = 2048 if role == "fwd" else 1024
    bq = next((b for b in (bq_cap, 1024, 512, 256, 128, 64, 32, 16, 8)
               if b <= bq_cap and S % b == 0), None)
    bk = next((b for b in (1024, 512, 256, 128, 64, 32, 16, 8)
               if S % b == 0), None)
    if bq is None or bk is None:
        return None, None
    return min(bq, S), min(bk, S)


def _select_blocks(BH: int, S: int, D: int, dtype, causal: bool, role: str = "fwd"):
    """Heuristic default, upgraded by the autotune cache when tuning is on
    (phi/kernels/autotune AutoTuneBase::PickBestAlgorithm analog). Measured
    configs are keyed by (BH, S, D, dtype, causal, role); fwd and bwd pick
    independently."""
    from . import autotune

    default = _pick_blocks(S, role)
    if default[0] is None:
        return default
    bq_cap = 2048 if role == "fwd" else 1024
    candidates = [(bq, bk)
                  for bq in (2048, 1024, 512, 256, 128)
                  if bq <= bq_cap and S % bq == 0
                  for bk in (1024, 512, 256, 128) if S % bk == 0]
    if default not in candidates:
        # measurement must be able to pick (and so can only improve on) the
        # heuristic default, else enabling autotune could lock in a slower cfg
        candidates.insert(0, default)

    def make_run(cfg):
        bq, bk = cfg
        q = jnp.zeros((BH, S, 1, D), dtype)
        if role == "bwd":
            # measure the kernels the pick actually configures: dq + dkv
            qt = jnp.zeros((BH, S, D), dtype)
            lse = jnp.zeros((BH, S), jnp.float32)

            def bwd_fn(qt):
                dq, dk, dv = _bwd(causal, 1.0, bq, bk,
                                  (qt, qt, qt, qt, lse), q)
                # consume all three grads so neither pallas_call is DCE'd —
                # the pick must price dq AND dkv together
                return (dq[0, 0, 0].astype(jnp.float32)
                        + dk[0, 0, 0].astype(jnp.float32)
                        + dv[0, 0, 0].astype(jnp.float32))

            fn = jax.jit(bwd_fn)
            return lambda: fn(qt)
        fn = jax.jit(lambda q: _fwd(q, q, q, causal, 1.0, bq, bk)[0])
        return lambda: fn(q)

    picked = autotune.pick_best(
        "flash_attention", (BH, S, D, str(jnp.dtype(dtype)), bool(causal), role),
        candidates, make_run, default=default)
    return tuple(picked)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    B, S, H, D = q.shape
    bq, bk = _select_blocks(B * H, S, D, q.dtype, causal)
    o, _, _ = _fwd(q, k, v, causal, scale, bq, bk)
    return jnp.swapaxes(o.reshape(B, H, S, D), 1, 2)


def _flash_fwd_rule(q, k, v, causal, scale):
    B, S, H, D = q.shape
    bq, bk = _select_blocks(B * H, S, D, q.dtype, causal)
    o, lse, (qt, kt, vt) = _fwd(q, k, v, causal, scale, bq, bk)
    out = jnp.swapaxes(o.reshape(B, H, S, D), 1, 2)
    return out, (qt, kt, vt, o, lse)


def _flash_bwd_rule(causal, scale, res, g):
    BH, S, D = res[0].shape
    bq, bk = _select_blocks(BH, S, D, res[0].dtype, causal, role="bwd")
    return _bwd(causal, scale, bq, bk, res, g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_fwd(q, k, v, causal: bool = False, scale: float = None):
    """[B, S, H, D] flash attention; falls back to None-signal if unsupported
    (caller uses the jnp reference path)."""
    from jax.ad_checkpoint import checkpoint_name

    B, S, H, D = q.shape
    if _pick_blocks(S)[0] is None:
        raise ValueError(f"flash_attention: seq len {S} not divisible by a supported block")
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    # named for the 'save_flash' remat policy (fleet/recompute.py): a
    # checkpointed block can keep THIS output resident so its backward
    # replays only the cheap projections/elementwise, not the flash kernel
    return checkpoint_name(_flash(q, k, v, causal, scale), "flash_out")
