"""Fused AdamW Pallas kernel (phi/kernels/gpu/fused_adam_kernel.cu analog):
moment update + bias correction + decoupled decay + param update in one HBM
pass per tensor. XLA fuses most of this already; the kernel removes the
remaining intermediate materializations for the biggest params."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _adamw_kernel(p_ref, g_ref, m_ref, v_ref, hyp_ref, p_out, m_out, v_out):
    lr = hyp_ref[0]
    b1, b2, eps, wd, b1p, b2p = hyp_ref[1], hyp_ref[2], hyp_ref[3], hyp_ref[4], hyp_ref[5]
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1 - b1) * g
    v = b2 * v_ref[:] + (1 - b2) * g * g
    m_hat = m / (1 - b1p)
    v_hat = v / (1 - b2p)
    p = p * (1.0 - lr * wd)
    p = p - lr * m_hat / (jnp.sqrt(v_hat) + eps)
    p_out[:] = p.astype(p_out.dtype)
    m_out[:] = m
    v_out[:] = v


def fused_adamw_update(param, grad, m, v, *, lr, beta1, beta2, eps, weight_decay, beta1_pow, beta2_pow):
    """One fused step for a single tensor; returns (new_param, new_m, new_v).
    beta*_pow are the *new* accumulated powers (beta^t)."""
    shape = param.shape
    flat = lambda a: a.reshape(-1)
    n = param.size
    hyp = jnp.stack(
        [
            jnp.float32(lr),
            jnp.float32(beta1),
            jnp.float32(beta2),
            jnp.float32(eps),
            jnp.float32(weight_decay),
            jnp.asarray(beta1_pow, jnp.float32).reshape(()),
            jnp.asarray(beta2_pow, jnp.float32).reshape(()),
        ]
    )

    def kernel(p_ref, g_ref, m_ref, v_ref, hyp_ref, p_out, m_out, v_out):
        lr_, b1, b2 = hyp_ref[0], hyp_ref[1], hyp_ref[2]
        eps_, wd, b1p, b2p = hyp_ref[3], hyp_ref[4], hyp_ref[5], hyp_ref[6]
        p = p_ref[:].astype(jnp.float32)
        g = g_ref[:].astype(jnp.float32)
        mm = b1 * m_ref[:] + (1 - b1) * g
        vv = b2 * v_ref[:] + (1 - b2) * g * g
        m_hat = mm / (1 - b1p)
        v_hat = vv / (1 - b2p)
        p = p * (1.0 - lr_ * wd) - lr_ * m_hat / (jnp.sqrt(v_hat) + eps_)
        p_out[:] = p.astype(p_out.dtype)
        m_out[:] = mm
        v_out[:] = vv

    new_p, new_m, new_v = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((n,), param.dtype),
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((n,), jnp.float32),
        ],
        interpret=_interpret(),
    )(flat(param), flat(grad), flat(m).astype(jnp.float32), flat(v).astype(jnp.float32), hyp)
    return new_p.reshape(shape), new_m.reshape(shape), new_v.reshape(shape)
