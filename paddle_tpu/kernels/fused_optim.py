"""Fused AdamW Pallas kernel (phi/kernels/gpu/fused_adam_kernel.cu analog):
moment update + bias correction + decoupled decay + param update in one HBM
pass per tensor. XLA fuses most of this already; the kernel removes the
remaining intermediate materializations for the biggest params.

Layout: the flat tensor is padded to a (rows, 128)-lane grid and streamed
through VMEM in row blocks; hyperparameters ride in SMEM as scalars."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_BLOCK_ROWS = 512  # 512*128*4B = 256KB per operand; 7 operands ≈ 1.8MB VMEM


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _adamw_kernel(hyp_ref, p_ref, g_ref, m_ref, v_ref, p_out, m_out, v_out):
    lr, b1, b2 = hyp_ref[0], hyp_ref[1], hyp_ref[2]
    eps, wd, b1p, b2p = hyp_ref[3], hyp_ref[4], hyp_ref[5], hyp_ref[6]
    # all casts happen HERE, in VMEM: operands stream in at their NATIVE
    # dtypes (bf16 grads/moments under moment_dtype='bfloat16') — a
    # pre-kernel astype would materialize full f32 copies in HBM (~20 GB of
    # traffic per step at 674M params), which this kernel exists to avoid
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:].astype(jnp.float32) + (1 - b1) * g
    v = b2 * v_ref[:].astype(jnp.float32) + (1 - b2) * g * g
    m_hat = m / (1 - b1p)
    v_hat = v / (1 - b2p)
    p = p * (1.0 - lr * wd) - lr * m_hat / (jnp.sqrt(v_hat) + eps)
    p_out[:] = p.astype(p_out.dtype)
    m_out[:] = m.astype(m_out.dtype)
    v_out[:] = v.astype(v_out.dtype)


def fused_adamw_update(param, grad, m, v, *, lr, beta1, beta2, eps, weight_decay, beta1_pow, beta2_pow):
    """One fused step for a single tensor; returns (new_param, new_m, new_v).
    beta*_pow are the *new* accumulated powers (beta^t)."""
    shape = param.shape
    n = param.size
    rows = -(-n // _LANES)
    pad = rows * _LANES - n

    def to2d(a, dtype):
        a = a.reshape(-1).astype(dtype)
        if pad:
            a = jnp.pad(a, (0, pad))
        return a.reshape(rows, _LANES)

    hyp = jnp.stack(
        [
            jnp.asarray(lr, jnp.float32).reshape(()),
            jnp.float32(beta1),
            jnp.float32(beta2),
            jnp.float32(eps),
            jnp.float32(weight_decay),
            jnp.asarray(beta1_pow, jnp.float32).reshape(()),
            jnp.asarray(beta2_pow, jnp.float32).reshape(()),
        ]
    )

    br = min(_BLOCK_ROWS, rows)
    blk = lambda: pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    new_p, new_m, new_v = pl.pallas_call(
        _adamw_kernel,
        grid=(pl.cdiv(rows, br),),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            blk(),
            blk(),
            blk(),
            blk(),
        ],
        out_specs=[blk(), blk(), blk()],
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANES), param.dtype),
            jax.ShapeDtypeStruct((rows, _LANES), m.dtype),
            jax.ShapeDtypeStruct((rows, _LANES), v.dtype),
        ],
        interpret=_interpret(),
    )(hyp, to2d(param, param.dtype), to2d(grad, grad.dtype), to2d(m, m.dtype), to2d(v, v.dtype))

    unflat = lambda a: a.reshape(-1)[:n].reshape(shape)
    return unflat(new_p), unflat(new_m), unflat(new_v)
