"""Block-scaled gradient quantization (the EQuARX wire format).

Communication compression for gradient collectives: values are quantized
per contiguous block of `block_size` elements along the LAST dim to int8
with one f32 scale per block (amax/127), so the wire carries
1 + 4/block_size bytes per f32 value (~3.9x at block 128). The bf16 mode
is the conservative fallback — a plain downcast, 2x, no scales.

These are plain jnp ops (VPU element-wise work, fused by XLA into the
surrounding collective schedule), not Pallas kernels: the cost of the
quantized-reduce path is the collectives themselves, and keeping
quant/dequant as stock HLO lets the SPMD partitioner schedule them inside
the per-axis reduction stages that comm_opt emits.

Non-finite propagation contract (load-bearing for the fp16 GradScaler):
a NaN/Inf anywhere in a block must survive the quantize->dequant round
trip so the train step's overflow detector still trips. The scale is
computed as amax (no finite clamping), so a non-finite amax poisons the
whole block's dequantized values.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax.numpy as jnp

__all__ = ["quantize_block_scaled", "dequantize_block_scaled",
           "fit_block_size"]


def fit_block_size(C: int, block_size: int = 128) -> int:
    """Largest block that divides C and the requested block_size (their gcd).

    Grad buckets pad themselves to a granule, but activation exchanges (MoE
    token dispatch) quantize a model dim that may be smaller than the default
    block — e.g. d_model 64 under block 128 fits at 64 with double the scale
    overhead. The degenerate gcd (< 8: more than half the wire is scales)
    means the dim is not worth compressing; callers should fall back.
    """
    return math.gcd(int(C), int(block_size))


def quantize_block_scaled(
    v: jnp.ndarray, block_size: int = 128, dtype: str = "int8"
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """v [..., C] float -> (payload, scales).

    int8: payload int8 [..., C], scales f32 [..., C // block_size]; C must
    be a multiple of block_size. bf16: payload bf16 [..., C], scales None.
    """
    if dtype in ("bf16", "bfloat16"):
        return v.astype(jnp.bfloat16), None
    if dtype != "int8":
        raise ValueError(f"quantize dtype must be int8/bf16, got {dtype!r}")
    C = v.shape[-1]
    if C % block_size:
        raise ValueError(f"last dim {C} not a multiple of block {block_size}")
    v = v.astype(jnp.float32)
    b = v.reshape(v.shape[:-1] + (C // block_size, block_size))
    amax = jnp.max(jnp.abs(b), axis=-1)
    # maximum (not where) so a non-finite amax PROPAGATES into the scale;
    # the tiny floor only rescues all-zero blocks from 0/0
    scale = jnp.maximum(amax, jnp.float32(1e-30)) * jnp.float32(1.0 / 127.0)
    q = jnp.round(b / scale[..., None])
    q = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
    return q.reshape(v.shape), scale


def dequantize_block_scaled(
    q: jnp.ndarray, scales: Optional[jnp.ndarray], block_size: int = 128
) -> jnp.ndarray:
    """Inverse of quantize_block_scaled; always returns f32."""
    if scales is None:
        return q.astype(jnp.float32)
    C = q.shape[-1]
    b = q.astype(jnp.float32).reshape(q.shape[:-1] + (C // block_size, block_size))
    return (b * scales[..., None].astype(jnp.float32)).reshape(q.shape)
