"""Kernel Primitive API — the KPS analog (phi/kernels/primitive/, kps/:
block-level device-portable primitives so one kernel source targets multiple
backends; SURVEY §2.2).

TPU re-design: the portability target is Mosaic's tiling rules rather than
CUDA/XPU-KP. These helpers encode the layout discipline every Pallas TPU
kernel here follows — 128-lane trailing dimension, (8,128) float32 tiles,
flatten-arbitrary-shape-to-padded-2D — plus factory functions that turn a
plain jnp expression into a tiled elementwise or row-reduction kernel.
kernels/fused_optim.py and norms.py are hand-rolled instances of the same
patterns; new kernels should build on these.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANES = 128        # vector lane width (trailing-dim tile)
SUBLANES = 8       # float32 sublane count -> (8, 128) native tile
DEFAULT_BLOCK_ROWS = 512


def interpret() -> bool:
    """Pallas interpret mode off-TPU (tests on CPU)."""
    return jax.default_backend() not in ("tpu", "axon")


def pad_rows(n: int, lanes: int = LANES) -> int:
    """Rows of the [rows, lanes] 2D view holding n flat elements."""
    return -(-n // lanes)


def to_tiled_2d(a, lanes: int = LANES):
    """Flatten to [rows, lanes] with zero padding (ReadData analog: every
    kernel sees a lane-aligned 2D block regardless of logical shape)."""
    n = a.size
    rows = pad_rows(n, lanes)
    flat = a.reshape(-1)
    if rows * lanes != n:
        flat = jnp.pad(flat, (0, rows * lanes - n))
    return flat.reshape(rows, lanes)


def from_tiled_2d(a2d, shape: Sequence[int]):
    """Inverse of to_tiled_2d (WriteData analog)."""
    n = 1
    for s in shape:
        n *= int(s)
    return a2d.reshape(-1)[:n].reshape(shape)


def row_block_spec(block_rows: int, lanes: int = LANES) -> pl.BlockSpec:
    """1-D grid over row blocks of a [rows, lanes] view."""
    return pl.BlockSpec((block_rows, lanes), lambda i: (i, 0))


def elementwise_kernel(fn: Callable, block_rows: int = DEFAULT_BLOCK_ROWS):
    """Lift ``fn(*blocks) -> block`` (pure jnp, fp32 math) into a tiled
    Pallas kernel over any same-shaped operands (ElementwiseUnary/Binary/
    Ternary analog in one factory).

        scaled_residual = elementwise_kernel(lambda x, y, a: x + a * y)
        out = scaled_residual(x, y, alpha)          # any shape, any dtype
    """

    def kernel(*refs):
        ins, out_ref = refs[:-1], refs[-1]
        vals = [r[...].astype(jnp.float32) for r in ins]
        out_ref[...] = fn(*vals).astype(out_ref.dtype)

    @functools.wraps(fn)
    def call(*arrays):
        arrays = [jnp.asarray(a) for a in arrays]
        shape, dtype = arrays[0].shape, arrays[0].dtype
        for a in arrays[1:]:
            if a.shape != shape:
                raise ValueError(f"elementwise operands must share a shape; "
                                 f"got {shape} vs {a.shape}")
        tiled = [to_tiled_2d(a) for a in arrays]
        rows = tiled[0].shape[0]
        br = min(block_rows, rows)
        out = pl.pallas_call(
            kernel,
            grid=(pl.cdiv(rows, br),),
            in_specs=[row_block_spec(br)] * len(tiled),
            out_specs=row_block_spec(br),
            out_shape=jax.ShapeDtypeStruct((rows, LANES), dtype),
            interpret=interpret(),
        )(*tiled)
        return from_tiled_2d(out, shape)

    return call


def row_reduce_kernel(fn: Callable, init: float,
                      block_cols: int = 1024):
    """Lift a pairwise reduction ``fn(acc, block) -> acc`` over the LAST axis
    into a tiled kernel (Reduce<kps::AddFunctor> analog). The input is viewed
    as [rows, cols]; cols must be lane-aligned for the fast path, otherwise
    falls back to jnp.

        row_sum = row_reduce_kernel(lambda acc, x: acc + x.sum(-1), 0.0)
        out = row_sum(x)   # [..., cols] -> [...]
    """

    def kernel(x_ref, out_ref):
        # grid dim 1 walks col blocks sequentially (TPU grids iterate the
        # trailing dim innermost, in order), so the fp32 out block doubles as
        # the running accumulator across col blocks: VMEM holds only
        # (block_rows x block_cols) of x at a time, never the full row.
        ci = pl.program_id(1)

        @pl.when(ci == 0)
        def _init():
            out_ref[:, 0] = jnp.full((out_ref.shape[0],), init, jnp.float32)

        acc = out_ref[:, 0]
        out_ref[:, 0] = fn(acc, x_ref[...].astype(jnp.float32))

    def call(x):
        x = jnp.asarray(x)
        *lead, cols = x.shape
        rows = 1
        for s in lead:
            rows *= int(s)
        if cols % LANES or rows % SUBLANES:
            # layout-unfriendly shape: let XLA handle it
            acc = jnp.full(tuple(lead) or (), init, jnp.float32)
            return fn(acc.reshape(rows), x.reshape(rows, cols).astype(jnp.float32)) \
                .reshape(lead).astype(x.dtype)
        x2 = x.reshape(rows, cols)

        def divisor_block(limit, n, floor):
            b = min(limit, n)
            while n % b:  # n is a multiple of `floor`, so halving terminates
                b //= 2
            return max(b, floor)

        bc = divisor_block(block_cols, cols, LANES)
        br = divisor_block(DEFAULT_BLOCK_ROWS, rows, SUBLANES)
        out = pl.pallas_call(
            kernel,
            grid=(rows // br, cols // bc),
            in_specs=[pl.BlockSpec((br, bc), lambda i, j: (i, j))],
            out_specs=pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            interpret=interpret(),
        )(x2)
        return out.astype(x.dtype).reshape(lead)

    return call
