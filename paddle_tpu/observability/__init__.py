"""paddle_tpu.observability — framework-wide runtime telemetry.

A process-global metrics registry (counters / gauges / histograms with
labels, thread-safe snapshot/reset) plus a span tracer unified with
``paddle_tpu.profiler``'s host event recorder. Off by default behind
``FLAGS_observability``; see observability/README.md for the metric naming
scheme and the bench.py field mapping.

    import paddle_tpu
    paddle_tpu.observability.enable()
    ...train / run passes / collectives...
    print(paddle_tpu.observability.summary())
    paddle_tpu.observability.dump_jsonl("/tmp/metrics.jsonl")
"""

from . import instrument, metrics, tracing, training  # noqa: F401
from .instrument import record_collective, record_compile  # noqa: F401
from .metrics import (  # noqa: F401
    MetricsRegistry,
    counter,
    disable,
    dump_jsonl,
    enable,
    enabled,
    gauge,
    get_registry,
    histogram,
    reset,
    snapshot,
    summary,
)
from .tracing import clear_spans, export_chrome_trace, span, spans  # noqa: F401
from .training import record_step, record_window  # noqa: F401

__all__ = [
    "MetricsRegistry", "enabled", "enable", "disable",
    "counter", "gauge", "histogram", "snapshot", "reset", "get_registry",
    "summary", "dump_jsonl",
    "span", "spans", "clear_spans", "export_chrome_trace",
    "record_collective", "record_compile", "record_step", "record_window",
]
