"""paddle_tpu.observability — framework-wide runtime telemetry.

A process-global metrics registry (counters / gauges / histograms with
labels, thread-safe snapshot/reset) plus a span tracer unified with
``paddle_tpu.profiler``'s host event recorder. Off by default behind
``FLAGS_observability``; see observability/README.md for the metric naming
scheme and the bench.py field mapping.

    import paddle_tpu
    paddle_tpu.observability.enable()
    ...train / run passes / collectives...
    print(paddle_tpu.observability.summary())
    paddle_tpu.observability.dump_jsonl("/tmp/metrics.jsonl")
"""

from . import (  # noqa: F401
    aggregate,
    anatomy,
    attribution,
    export,
    flight_recorder,
    goodput,
    health,
    instrument,
    memory,
    metrics,
    tracing,
    training,
    xplane,
)
from .aggregate import fleet_report, render_report  # noqa: F401
from .attribution import (  # noqa: F401
    HardwareSpec,
    attribute,
    hardware_for_backend,
    site_report,
)
from .export import (  # noqa: F401
    MetricsExporter,
    get_exporter,
    start_exporter,
    stop_exporter,
)
from .flight_recorder import (  # noqa: F401
    FlightRecorder,
    get_flight_recorder,
    read_flight,
    record_event,
    start_flight_recorder,
    stop_flight_recorder,
)
from .goodput import GoodputMonitor  # noqa: F401
from .health import (  # noqa: F401
    EwmaDetector,
    HealthConfig,
    HealthMonitor,
    NonfiniteProvenance,
    param_group,
)
from .instrument import record_collective, record_compile  # noqa: F401
from .memory import (  # noqa: F401
    record_device_memory,
    record_executable,
    record_kv_cache,
    record_live_buffers,
)
from .metrics import (  # noqa: F401
    MetricsRegistry,
    counter,
    disable,
    dump_jsonl,
    enable,
    enabled,
    gauge,
    get_registry,
    hist_totals,
    histogram,
    reset,
    snapshot,
    summary,
)
from .tracing import (  # noqa: F401
    add_span_sink,
    clear_spans,
    export_chrome_trace,
    remove_span_sink,
    set_max_spans,
    span,
    spans,
)
from .training import record_step, record_window  # noqa: F401

__all__ = [
    "MetricsRegistry", "enabled", "enable", "disable",
    "counter", "gauge", "histogram", "snapshot", "reset", "get_registry",
    "summary", "dump_jsonl", "hist_totals",
    "span", "spans", "clear_spans", "export_chrome_trace",
    "add_span_sink", "remove_span_sink", "set_max_spans",
    "record_collective", "record_compile", "record_step", "record_window",
    "MetricsExporter", "start_exporter", "stop_exporter", "get_exporter",
    "FlightRecorder", "start_flight_recorder", "stop_flight_recorder",
    "get_flight_recorder", "read_flight", "record_event",
    "record_executable", "record_live_buffers", "record_device_memory",
    "record_kv_cache",
    "GoodputMonitor", "fleet_report", "render_report",
    "HealthMonitor", "HealthConfig", "EwmaDetector", "NonfiniteProvenance",
    "param_group",
    "HardwareSpec", "attribute", "hardware_for_backend", "site_report",
]
