"""Goodput / straggler monitor: where did the step time go?

Classifies training wall time into buckets using deltas of instrumentation
that already exists — no new probes in the hot path:

    data_wait   <- ``data.host_wait_seconds``   (data/feed.py)
    ckpt_block  <- ``ckpt.save.blocking_seconds`` (checkpoint/manager.py)
    comm        <- ``dist.collective.seconds``  (eager-face collectives)
    compute     <- step wall time minus the comm share (comm overlaps the
                   dispatch; data/ckpt stalls happen BETWEEN dispatches)

``ShardedTrainStep`` feeds ``observe_step`` once per dispatch. Outputs:

    train.goodput.seconds{bucket=...}  counters (cumulative attribution)
    train.goodput.fraction             gauge (compute / accounted wall)
    train.goodput.step_ratio           gauge (recent mean / window median)
    train.goodput.regression           counter (ratio crossed threshold)

The per-host straggler view (this host's step-time mean vs the fleet
median) lives in ``aggregate.py`` — it needs every host's dump, not one
process's registry.
"""

from __future__ import annotations

import statistics
from collections import deque
from typing import Dict, Optional

from . import metrics

_BUCKET_SOURCES = (
    ("data_wait", "data.host_wait_seconds"),
    ("ckpt_block", "ckpt.save.blocking_seconds"),
    ("comm", "dist.collective.seconds"),
)


class GoodputMonitor:
    """Rolling per-step classifier + step-time regression detector."""

    def __init__(self, window: int = 64, recent: int = 8,
                 regression_factor: float = 1.5):
        self.window = int(window)
        self.recent = max(1, int(recent))
        self.regression_factor = float(regression_factor)
        self._steps: deque = deque(maxlen=self.window)
        self._last: Dict[str, float] = {}
        self._totals: Dict[str, float] = {
            "compute": 0.0, "data_wait": 0.0, "ckpt_block": 0.0, "comm": 0.0}
        self._in_regression = False

    def _delta(self, hist_name: str) -> float:
        total, _ = metrics.hist_totals(hist_name)
        d = total - self._last.get(hist_name, 0.0)
        self._last[hist_name] = total
        return max(d, 0.0)

    def observe_step(self, seconds: float, steps: int = 1) -> Dict[str, float]:
        """Attribute one dispatch's wall time; returns the bucket seconds."""
        buckets = {name: self._delta(src) for name, src in _BUCKET_SOURCES}
        # comm time is spent INSIDE the dispatch window; stalls feeding or
        # checkpointing are extra wall time around it
        buckets["compute"] = max(seconds - buckets["comm"], 0.0)
        for name, v in buckets.items():
            if v:
                self._totals[name] += v
                metrics.counter("train.goodput.seconds", v, bucket=name)
        accounted = sum(self._totals.values())
        if accounted > 0:
            metrics.gauge("train.goodput.fraction",
                          self._totals["compute"] / accounted)
        self._observe_regression(seconds / max(steps, 1))
        return buckets

    def _observe_regression(self, per_step: float):
        self._steps.append(per_step)
        if len(self._steps) < max(self.recent * 2, 8):
            return
        baseline = statistics.median(self._steps)
        recent = list(self._steps)[-self.recent:]
        ratio = (sum(recent) / len(recent)) / baseline if baseline > 0 else 1.0
        metrics.gauge("train.goodput.step_ratio", ratio)
        regressed = ratio > self.regression_factor
        if regressed and not self._in_regression:
            # count edges, not samples: one slowdown event = one increment
            metrics.counter("train.goodput.regression", 1)
        self._in_regression = regressed

    def goodput_fraction(self) -> Optional[float]:
        accounted = sum(self._totals.values())
        return self._totals["compute"] / accounted if accounted > 0 else None


_monitor: Optional[GoodputMonitor] = None


def get_monitor() -> GoodputMonitor:
    global _monitor
    if _monitor is None:
        _monitor = GoodputMonitor()
    return _monitor


def reset_monitor():
    global _monitor
    _monitor = None


def observe_step(seconds: float, steps: int = 1):
    """Flag-gated module face ShardedTrainStep calls once per dispatch."""
    if not metrics.enabled():
        return
    get_monitor().observe_step(seconds, steps=steps)
