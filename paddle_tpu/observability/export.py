"""Periodic per-host JSONL metric export — the multi-host aggregation feed.

Each host runs one ``MetricsExporter``: a daemon thread that every
``interval_s`` appends ONE JSON line (a "flush") to
``<directory>/metrics-host<NNNNN>.jsonl``:

    {"schema": "paddle_tpu.metrics.v1", "host": 3, "pid": 4711,
     "ts": 1722841200.0, "seq": 17, "metrics": [<registry records>]}

``metrics`` carries the full cumulative registry (counters/gauges and
histograms with bucket counts), so any single line is a complete snapshot —
the merge side (``aggregate.py`` / ``tools/telemetry_report.py``) takes the
LAST line per host for fleet totals and the line sequence for time series.
Append-only + one line per flush means a crash can lose at most the final
partial line; every earlier flush stays readable.

Self-accounting: ``obs.export.flushes`` / ``obs.export.bytes`` /
``obs.export.errors`` counters and an ``obs.export.flush_seconds``
histogram (the bench.py "export overhead" row reads the latter).
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Optional

from . import metrics

SCHEMA = "paddle_tpu.metrics.v1"


def _default_host() -> int:
    env = os.environ.get("PT_HOST_ID")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def host_dump_path(directory: str, host: int) -> str:
    return os.path.join(directory, f"metrics-host{host:05d}.jsonl")


class MetricsExporter:
    """Append-only periodic JSONL flusher for one host's registry."""

    def __init__(self, directory: str, interval_s: float = 30.0,
                 host: Optional[int] = None):
        self.directory = directory
        self.interval_s = float(interval_s)
        self.host = _default_host() if host is None else int(host)
        self.path = host_dump_path(directory, self.host)
        self._seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one flush: serialize the whole registry as a single line --
    def flush(self, reason: str = "interval") -> Optional[str]:
        t0 = time.perf_counter()
        try:
            line = json.dumps({
                "schema": SCHEMA,
                "host": self.host,
                "pid": os.getpid(),
                "ts": time.time(),
                "seq": self._seq,
                "reason": reason,
                "metrics": metrics.get_registry().records(),
            })
            os.makedirs(self.directory, exist_ok=True)
            with self._lock:
                with open(self.path, "a") as f:
                    f.write(line + "\n")
                self._seq += 1
        except Exception:
            metrics.counter("obs.export.errors", 1)
            return None
        metrics.counter("obs.export.flushes", 1)
        metrics.counter("obs.export.bytes", len(line) + 1)
        metrics.histogram("obs.export.flush_seconds",
                          time.perf_counter() - t0)
        return self.path

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self.flush()

    def start(self) -> "MetricsExporter":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pt-metrics-exporter", daemon=True)
            self._thread.start()
        return self

    def stop(self, final_flush: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_flush:
            self.flush(reason="final")


_exporter: Optional[MetricsExporter] = None
_atexit_registered = False


def _atexit_flush():
    exp = _exporter
    if exp is not None:
        exp.stop(final_flush=True)


def start_exporter(directory: str, interval_s: float = 30.0,
                   host: Optional[int] = None) -> Optional[MetricsExporter]:
    """Start (or replace) this process's periodic exporter. Returns None —
    starting nothing — when observability is off."""
    global _exporter, _atexit_registered
    if not metrics.enabled():
        return None
    if _exporter is not None:
        _exporter.stop(final_flush=False)
    _exporter = MetricsExporter(directory, interval_s, host).start()
    if not _atexit_registered:
        atexit.register(_atexit_flush)
        _atexit_registered = True
    return _exporter


def stop_exporter(final_flush: bool = True):
    global _exporter
    if _exporter is not None:
        _exporter.stop(final_flush=final_flush)
        _exporter = None


def get_exporter() -> Optional[MetricsExporter]:
    return _exporter
