"""Lightweight span tracer, unified with the profiler's host recorder.

``span(name, **labels)`` times a region and, when ``FLAGS_observability`` is
on:

* records a ``<name>.seconds`` histogram into the metrics registry,
* forwards the span into ``profiler._HostEventRecorder`` — the SAME buffer
  ``profiler.RecordEvent`` writes — so an active ``profiler.Profiler`` merges
  observability spans into its ``export_chrome_tracing`` output for free
  (no second recorder, no duplicate span type), and
* appends to a bounded local buffer so ``export_chrome_trace`` can write a
  chrome://tracing JSON even when no Profiler is attached.

With the flag off, ``span`` yields immediately: no timing, no events, no
registry entries.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List

from ..profiler.profiler import _recorder
from . import metrics

_MAX_SPANS = 65536
_spans: deque = deque(maxlen=_MAX_SPANS)
_lock = threading.Lock()


def _span_name(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


@contextmanager
def span(name: str, **labels):
    """Time a region; no-op (single flag check) when observability is off."""
    if not metrics.enabled():
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        metrics.histogram(f"{name}.seconds", t1 - t0, **labels)
        full = _span_name(name, labels)
        # no-ops unless a Profiler is in a RECORD state — the merge seam
        _recorder.record(full, t0, t1)
        with _lock:
            _spans.append({
                "name": full,
                "ts": t0 * 1e6,
                "dur": (t1 - t0) * 1e6,
                "tid": threading.get_ident() % 100000,
            })


def spans() -> List[Dict[str, Any]]:
    """Copy of the local span buffer (most recent _MAX_SPANS)."""
    with _lock:
        return list(_spans)


def clear_spans():
    with _lock:
        _spans.clear()


def export_chrome_trace(path: str) -> str:
    """Write the local span buffer as chrome://tracing JSON — the same event
    schema profiler.export_chrome_tracing emits, so the files are
    interchangeable in the trace viewer."""
    events = [
        {"name": e["name"], "ph": "X", "ts": e["ts"], "dur": e["dur"],
         "pid": os.getpid(), "tid": e["tid"]}
        for e in spans()
    ]
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path
