"""Lightweight span tracer, unified with the profiler's host recorder.

``span(name, **labels)`` times a region and, when ``FLAGS_observability`` is
on:

* records a ``<name>.seconds`` histogram into the metrics registry,
* forwards the span into ``profiler._HostEventRecorder`` — the SAME buffer
  ``profiler.RecordEvent`` writes — so an active ``profiler.Profiler`` merges
  observability spans into its ``export_chrome_tracing`` output for free
  (no second recorder, no duplicate span type), and
* appends to a bounded local buffer so ``export_chrome_trace`` can write a
  chrome://tracing JSON even when no Profiler is attached.

With the flag off, ``span`` yields immediately: no timing, no events, no
registry entries.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List

from ..profiler.profiler import _recorder
from . import metrics

_MAX_SPANS = 65536
_spans: deque = deque(maxlen=_MAX_SPANS)
_lock = threading.Lock()
# consumers (the flight recorder) that want every finished span as it lands;
# mutated only under _lock, iterated on a local copy
_sinks: List[Callable[[Dict[str, Any]], None]] = []


def add_span_sink(fn: Callable[[Dict[str, Any]], None]):
    """Register a callable invoked with every finished span event dict."""
    with _lock:
        if fn not in _sinks:
            _sinks.append(fn)


def remove_span_sink(fn: Callable[[Dict[str, Any]], None]):
    with _lock:
        if fn in _sinks:
            _sinks.remove(fn)


def set_max_spans(n: int):
    """Resize the bounded span ring (keeps the most recent entries)."""
    global _spans
    with _lock:
        _spans = deque(_spans, maxlen=max(1, int(n)))


def _span_name(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


@contextmanager
def span(name: str, **labels):
    """Time a region; no-op (single flag check) when observability is off."""
    if not metrics.enabled():
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        metrics.histogram(f"{name}.seconds", t1 - t0, **labels)
        full = _span_name(name, labels)
        # no-ops unless a Profiler is in a RECORD state — the merge seam
        _recorder.record(full, t0, t1)
        event = {
            "name": full,
            "ts": t0 * 1e6,
            "dur": (t1 - t0) * 1e6,
            "tid": threading.get_ident() % 100000,
        }
        with _lock:
            dropped = (_spans.maxlen is not None
                       and len(_spans) == _spans.maxlen)
            _spans.append(event)
            sinks = list(_sinks)
        if dropped:
            # the ring silently evicted its oldest span — make the loss
            # visible so long runs know the buffer undersized
            metrics.counter("obs.trace.dropped", 1)
        for sink in sinks:
            try:
                sink(event)
            except Exception:
                metrics.counter("obs.trace.sink_errors", 1)


def spans() -> List[Dict[str, Any]]:
    """Copy of the local span buffer (most recent _MAX_SPANS)."""
    with _lock:
        return list(_spans)


def clear_spans():
    with _lock:
        _spans.clear()


def export_chrome_trace(path: str) -> str:
    """Write the local span buffer as chrome://tracing JSON — the same event
    schema profiler.export_chrome_tracing emits, so the files are
    interchangeable in the trace viewer."""
    events = [
        {"name": e["name"], "ph": "X", "ts": e["ts"], "dur": e["dur"],
         "pid": os.getpid(), "tid": e["tid"]}
        for e in spans()
    ]
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path
