"""Shared instrumentation helpers for the hot layers.

Collective accounting (the GSPMD/EQuARX-style per-collective byte/latency
attribution) and jit compile-cache accounting. Every helper gates on
``metrics.enabled()`` itself, so call sites stay one line and pay only the
flag check when observability is off.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from . import metrics


def payload_bytes(x: Any) -> Optional[int]:
    """Estimated payload size of a Tensor / jax array / tracer / ndarray.

    Works at trace time too: abstract values carry shape+dtype, which is all
    the estimate needs (bytes moved scale with the payload; the per-algorithm
    constant — e.g. ring all-reduce's 2(n-1)/n — is left to the reader)."""
    try:
        v = getattr(x, "_value", x)
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is None or dtype is None:
            return None
        n = 1
        for d in shape:
            n *= int(d)
        return n * np.dtype(dtype).itemsize
    except Exception:
        return None


def record_collective(op: str, value: Any = None, nbytes: Optional[int] = None,
                      seconds: Optional[float] = None, face: str = "eager"):
    """One collective issued: count it, account payload bytes, and (eager
    face only — the traced face records at trace time, once per compile)
    its host-observed latency."""
    if not metrics.enabled():
        return
    if nbytes is None and value is not None:
        nbytes = payload_bytes(value)
    metrics.counter("dist.collective.calls", 1, op=op, face=face)
    if nbytes:
        metrics.counter("dist.collective.bytes", nbytes, op=op, face=face)
    if seconds is not None:
        metrics.histogram("dist.collective.seconds", seconds, op=op, face=face)


def record_compile(site: str, seconds: Optional[float] = None,
                   cache_hit: bool = False):
    """Compile-cache accounting: a hit bumps ``jit.compile.cache_hit``; a
    miss bumps ``jit.compile.cache_miss`` and, when the caller timed the
    compiling call, observes ``jit.compile.seconds``."""
    if not metrics.enabled():
        return
    if cache_hit:
        metrics.counter("jit.compile.cache_hit", 1, site=site)
    else:
        metrics.counter("jit.compile.cache_miss", 1, site=site)
        if seconds is not None:
            metrics.histogram("jit.compile.seconds", seconds, site=site)


class TimedFirstCall:
    """Wrap a jitted callable so its FIRST invocation (trace + XLA compile;
    jax blocks until the executable exists) is observed as compile seconds.
    Attribute access (``.lower`` etc.) passes through."""

    __slots__ = ("_fn", "_site", "_warm")

    def __init__(self, fn, site: str):
        self._fn = fn
        self._site = site
        self._warm = False

    def __call__(self, *args, **kwargs):
        if self._warm or not metrics.enabled():
            return self._fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        self._warm = True
        metrics.histogram("jit.compile.seconds", time.perf_counter() - t0,
                          site=self._site)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)
