"""Fleet-wide merge of per-host telemetry dumps (the multi-host view).

Pure stdlib on purpose: ``tools/telemetry_report.py`` imports THIS module
standalone (synthetic-package trick, same as tools/comm_plan.py) so dumps
copied off a TPU fleet merge on any laptop with no jax — which is why this
file mirrors ``metrics._BUCKET_BOUNDS`` instead of importing it (a test
pins the two constants equal) and uses no relative imports.

Inputs: per-host files written by ``observability.export.MetricsExporter``
(one cumulative-snapshot JSON line per flush, ``paddle_tpu.metrics.v1``)
— or plain ``dump_jsonl`` files (one record per line), treated as a single
flush. Merge semantics:

    counters   — summed across hosts (cumulative totals add)
    gauges     — fleet mean/min/max + per-host values (a gauge is a level)
    histograms — bucket-wise count addition, min/max combined, fleet
                 percentiles re-estimated from the merged buckets
    stragglers — per-host ``train.step.seconds`` mean vs the fleet median
                 (delta seconds + ratio), the "host 13 is 1.4x slower" row
    divergence — per-host ``health.grad_norm{group=_global}`` vs the fleet
                 median plus per-host ``health.anomaly`` totals: one host's
                 numerics drifting (stale data shard, flaky HBM) shows as
                 a skew row before it shows as a NaN
    serving_health — per-replica ``serving.requests.active`` /
                 ``serving.kv.page_utilization`` levels (the multi-replica
                 routing view)
"""

from __future__ import annotations

import json
import math
import os
import re
import statistics
from typing import Any, Dict, List, Optional

# mirrors paddle_tpu.observability.metrics._BUCKET_BOUNDS (decade bounds,
# seconds); kept in sync by tests/test_telemetry.py
BUCKET_BOUNDS = tuple(10.0 ** e for e in range(-7, 4))

STEP_HIST = "train.step.seconds"

# the divergence-skew view keys on the global grad-norm gauge emitted by
# observability.health.HealthMonitor
HEALTH_GRAD_GLOBAL = "health.grad_norm{group=_global}"
HEALTH_ANOMALY = "health.anomaly"

# per-replica serving levels folded into the fleet view
SERVING_HEALTH_GAUGES = ("serving.requests.active",
                         "serving.kv.page_utilization")


def _render_key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def percentile_from_buckets(buckets: List[int], count: int,
                            mn: float, mx: float, q: float) -> float:
    """Same estimator as metrics._Hist.percentile, over merged buckets."""
    if not count:
        return 0.0
    target = q * count
    cum = 0
    for i, n in enumerate(buckets):
        if not n:
            continue
        if cum + n >= target:
            lo = BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
            hi = BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS) else mx
            lo = max(lo, mn)
            hi = min(hi, mx)
            if hi < lo:
                hi = lo
            return lo + (hi - lo) * ((target - cum) / n)
        cum += n
    return mx


def load_host_dump(path: str, default_host: int = 0) -> Dict[str, Any]:
    """Parse one per-host file into {"host": int, "flushes": [...]} where
    each flush is {"ts", "seq", "metrics": [records]}. Accepts exporter
    flush lines and bare dump_jsonl record lines; tolerates a torn tail."""
    host: Optional[int] = None
    m = re.search(r"host(\d+)", os.path.basename(path))
    if m:
        host = int(m.group(1))
    flushes: List[Dict[str, Any]] = []
    bare: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn final line from a crash — earlier flushes hold
            if "metrics" in obj:
                if host is None and "host" in obj:
                    host = int(obj["host"])
                flushes.append({"ts": obj.get("ts"), "seq": obj.get("seq"),
                                "metrics": obj["metrics"]})
            elif "type" in obj:
                bare.append(obj)
    if bare:
        flushes.append({"ts": bare[0].get("ts"), "seq": 0, "metrics": bare})
    return {"host": default_host if host is None else host,
            "flushes": flushes}


def merge_histograms(dicts: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Bucket-wise merge of histogram records (counts add, extrema
    combine); fleet percentiles re-estimated from the merged buckets."""
    count = sum(int(d.get("count", 0)) for d in dicts)
    total = sum(float(d.get("sum", 0.0)) for d in dicts)
    nonempty = [d for d in dicts if d.get("count")]
    mn = min((float(d["min"]) for d in nonempty), default=0.0)
    mx = max((float(d["max"]) for d in nonempty), default=0.0)
    out = {"count": count, "sum": total,
           "avg": total / count if count else 0.0, "min": mn, "max": mx}
    blists = [d.get("buckets") for d in nonempty]
    if blists and all(b is not None for b in blists):
        width = max(len(b) for b in blists)
        merged = [0] * width
        for b in blists:
            for i, n in enumerate(b):
                merged[i] += int(n)
        out["buckets"] = merged
        for q, k in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
            out[k] = percentile_from_buckets(merged, count, mn, mx, q)
    return out


def _host_step_mean(records: List[Dict[str, Any]]) -> Optional[float]:
    total, count = 0.0, 0
    for r in records:
        if r.get("type") == "histogram" and r.get("name") == STEP_HIST:
            total += float(r.get("sum", 0.0))
            count += int(r.get("count", 0))
    return total / count if count else None


def fleet_report(paths: List[str]) -> Dict[str, Any]:
    """Merge ≥1 per-host dumps into one fleet view: summed counters,
    per-host gauges, merged histograms, time series, straggler deltas."""
    hosts: Dict[int, Dict[str, Any]] = {}
    for i, path in enumerate(sorted(paths)):
        dump = load_host_dump(path, default_host=i)
        h = dump["host"]
        while h in hosts:  # two files claiming one host id — keep both
            h += 1000
        hosts[h] = dump

    counters: Dict[str, Dict[str, Any]] = {}
    gauges: Dict[str, Dict[str, Any]] = {}
    hist_per_host: Dict[str, Dict[int, Dict[str, Any]]] = {}
    series: Dict[str, List[Dict[str, Any]]] = {}
    step_means: Dict[int, float] = {}

    for h, dump in sorted(hosts.items()):
        if not dump["flushes"]:
            continue
        # the LAST flush is the cumulative state; earlier ones feed series
        for flush in dump["flushes"]:
            for r in flush["metrics"]:
                if r.get("type") in ("counter", "gauge"):
                    key = _render_key(r.get("name", "?"), r.get("labels", {}))
                    series.setdefault(key, []).append(
                        {"host": h, "ts": flush.get("ts"),
                         "seq": flush.get("seq"), "value": r.get("value")})
        last = dump["flushes"][-1]["metrics"]
        for r in last:
            key = _render_key(r.get("name", "?"), r.get("labels", {}))
            typ = r.get("type")
            if typ == "counter":
                c = counters.setdefault(key, {"total": 0, "per_host": {}})
                c["total"] += r.get("value", 0)
                c["per_host"][h] = r.get("value", 0)
            elif typ == "gauge":
                g = gauges.setdefault(key, {"per_host": {}})
                g["per_host"][h] = r.get("value")
            elif typ == "histogram":
                hist_per_host.setdefault(key, {})[h] = {
                    k: v for k, v in r.items()
                    if k not in ("type", "name", "labels")}
        mean = _host_step_mean(last)
        if mean is not None:
            step_means[h] = mean

    for g in gauges.values():
        vals = [v for v in g["per_host"].values() if v is not None]
        if vals:
            g["mean"] = sum(vals) / len(vals)
            g["min"] = min(vals)
            g["max"] = max(vals)

    histograms = {key: {**merge_histograms(list(per.values())),
                        "per_host": per}
                  for key, per in hist_per_host.items()}

    stragglers: List[Dict[str, Any]] = []
    if step_means:
        med = statistics.median(step_means.values())
        for h, mean in sorted(step_means.items()):
            stragglers.append({
                "host": h, "mean_step_s": mean,
                "delta_s": mean - med,
                "ratio": mean / med if med > 0 else 1.0})
        stragglers.sort(key=lambda s: -s["ratio"])

    # per-host numerics skew: global grad-norm gauge vs fleet median +
    # anomaly totals. A non-finite norm (a host mid-divergence) sorts first.
    anomaly_totals: Dict[int, int] = {}
    for key, c in counters.items():
        if key.split("{", 1)[0] == HEALTH_ANOMALY:
            for h, v in c["per_host"].items():
                anomaly_totals[h] = anomaly_totals.get(h, 0) + int(v or 0)
    divergence: List[Dict[str, Any]] = []
    gnorms = {h: v for h, v in
              gauges.get(HEALTH_GRAD_GLOBAL, {}).get("per_host", {}).items()
              if v is not None}
    if gnorms or anomaly_totals:
        finite = [v for v in gnorms.values()
                  if isinstance(v, (int, float)) and v == v
                  and abs(v) != float("inf")]
        med = statistics.median(finite) if finite else None
        for h in sorted(set(gnorms) | set(anomaly_totals)):
            v = gnorms.get(h)
            nonfin = v is not None and not (
                isinstance(v, (int, float)) and v == v
                and abs(v) != float("inf"))
            row = {"host": h, "grad_norm": v,
                   "anomalies": anomaly_totals.get(h, 0),
                   "nonfinite": nonfin}
            if med is not None and v is not None and not nonfin and med > 0:
                row["delta"] = v - med
                row["ratio"] = v / med
            divergence.append(row)
        divergence.sort(key=lambda r: (not r["nonfinite"],
                                       -r.get("ratio", 1.0),
                                       -r["anomalies"]))

    serving_health = {key: gauges[key] for key in sorted(gauges)
                      if key.split("{", 1)[0] in SERVING_HEALTH_GAUGES}

    return {"hosts": sorted(hosts), "counters": counters, "gauges": gauges,
            "histograms": histograms, "series": series,
            "stragglers": stragglers, "divergence": divergence,
            "serving_health": serving_health}


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float) and not math.isfinite(v):
        return str(v)  # a mid-divergence host's gauge IS nan/inf
    if isinstance(v, float) and v != int(v):
        return f"{v:.6g}"
    try:
        return f"{int(v)}"
    except (TypeError, ValueError):
        return str(v)


def render_report(report: Dict[str, Any], grep: str = "") -> str:
    """Text rendering of a fleet_report (tools/telemetry_report.py)."""
    lines = [f"hosts: {', '.join(str(h) for h in report['hosts'])}"]
    cs = {k: v for k, v in report["counters"].items()
          if not grep or grep in k}
    if cs:
        lines += ["", f"{'Counter (fleet total)':<52}{'Total':>12}  per-host",
                  "-" * 92]
        for k in sorted(cs):
            per = " ".join(f"{h}:{_fmt(v)}"
                           for h, v in sorted(cs[k]["per_host"].items()))
            lines.append(f"{k[:51]:<52}{_fmt(cs[k]['total']):>12}  {per}")
    gs = {k: v for k, v in report["gauges"].items() if not grep or grep in k}
    if gs:
        lines += ["", f"{'Gauge':<44}{'Mean':>12}{'Min':>12}{'Max':>12}",
                  "-" * 80]
        for k in sorted(gs):
            g = gs[k]
            lines.append(f"{k[:43]:<44}{_fmt(g.get('mean')):>12}"
                         f"{_fmt(g.get('min')):>12}{_fmt(g.get('max')):>12}")
    hs = {k: v for k, v in report["histograms"].items()
          if not grep or grep in k}
    if hs:
        lines += ["", f"{'Histogram (merged)':<40}{'Count':>8}{'Avg':>12}"
                      f"{'p50':>12}{'p95':>12}{'p99':>12}", "-" * 96]
        for k in sorted(hs):
            h = hs[k]
            lines.append(f"{k[:39]:<40}{_fmt(h['count']):>8}"
                         f"{_fmt(h['avg']):>12}{_fmt(h.get('p50')):>12}"
                         f"{_fmt(h.get('p95')):>12}{_fmt(h.get('p99')):>12}")
    if report["stragglers"]:
        lines += ["", f"{'Straggler view (train.step.seconds)':<40}"
                      f"{'mean':>12}{'delta':>12}{'ratio':>8}", "-" * 72]
        for s in report["stragglers"]:
            lines.append(f"host {s['host']:<35}{_fmt(s['mean_step_s']):>12}"
                         f"{_fmt(s['delta_s']):>12}{s['ratio']:>8.3f}")
    if report.get("divergence"):
        lines += ["", f"{'Divergence view (health.grad_norm _global)':<44}"
                      f"{'grad_norm':>12}{'ratio':>8}{'anomalies':>10}",
                  "-" * 74]
        for d in report["divergence"]:
            ratio = (f"{d['ratio']:.3f}" if "ratio" in d
                     else ("NONFIN" if d["nonfinite"] else "-"))
            lines.append(f"host {d['host']:<39}{_fmt(d['grad_norm']):>12}"
                         f"{ratio:>8}{d['anomalies']:>10}")
    sv = report.get("serving_health") or {}
    if sv:
        lines += ["", f"{'Serving health (per replica)':<44}{'Mean':>12}"
                      f"{'Min':>12}{'Max':>12}", "-" * 80]
        for k in sorted(sv):
            g = sv[k]
            lines.append(f"{k[:43]:<44}{_fmt(g.get('mean')):>12}"
                         f"{_fmt(g.get('min')):>12}{_fmt(g.get('max')):>12}")
    return "\n".join(lines)
