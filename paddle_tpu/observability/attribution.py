"""Roofline attribution: predicted step-time floors vs measured device time.

Per site (a bench config or an analysis-corpus entry point) this model
combines the three cost numbers the earlier tiers already produce —

    flops       <- ``compiled.cost_analysis()``            (compute)
    hbm_bytes   <- cost_analysis bytes accessed / the train-traffic
                   estimator below                          (HBM)
    wire_bytes  <- the HLO audit's exact per-collective
                   receive-side accounting
                   (``tools/hlo_baseline.json``)            (ICI)

— into a predicted time floor per resource (``t_r = work_r / peak_r``),
names the **binding resource** (the largest floor: the roofline wall the
site is up against), and reconciles the floor against measured time: the
XPlane op table on device (``observability/xplane.py``) or, portably, the
``train.step.seconds`` histogram / goodput buckets from a metrics dump.
``gap = measured / floor`` reads directly: 1.0 is the roofline, 2.0 means
half the step is not explained by the binding resource and is worth
hunting (dispatch, stalls, non-overlapped transfers).

Stdlib-only BY CONTRACT, like ``aggregate.py``: ``tools/perf_report.py``
imports this module through the synthetic-package trick with no jax
installed, so hardware peaks are mirrored constants (a test pins the TPU
peak equal to ``training.peak_flops``) and metric recording goes through
a lazily imported, failure-tolerant hook.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional

SCHEMA = "paddle_tpu.attribution.v1"

#: resource order also used for deterministic binding tie-breaks
RESOURCES = ("compute", "hbm", "ici")

#: default reconciliation tolerances — mirrors analysis/hlo_audit.py
#: (WIRE_TOLERANCE / HBM_TOLERANCE); a test pins the pairs equal
WIRE_TOLERANCE = 0.10
HBM_TOLERANCE = 0.05


@dataclass(frozen=True)
class HardwareSpec:
    """Per-chip peaks the floors divide by."""

    name: str
    peak_flops: float          # FLOP/s (bf16 MXU peak on TPU)
    hbm_bytes_per_s: float     # HBM bandwidth
    ici_bytes_per_s: float     # per-chip interconnect bandwidth

    def as_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "peak_flops": self.peak_flops,
                "hbm_bytes_per_s": self.hbm_bytes_per_s,
                "ici_bytes_per_s": self.ici_bytes_per_s}


#: v5e: 197 TF/s bf16 (mirrors training.peak_flops), 819 GB/s HBM,
#: 1600 Gb/s aggregate ICI per chip. The CPU row is a NOMINAL scale so
#: tiny CI runs produce well-formed (clearly-labeled) reports, not a
#: claim about the host.
HW_SPECS: Dict[str, HardwareSpec] = {
    "tpu": HardwareSpec("tpu-v5e", 197e12, 819e9, 200e9),
    "axon": HardwareSpec("tpu-v5e", 197e12, 819e9, 200e9),
    "cpu": HardwareSpec("cpu-nominal", 1e12, 50e9, 10e9),
}


def hardware_for_backend(backend: str) -> HardwareSpec:
    """HardwareSpec for a jax backend name; ``cpu_fallback`` (the bench
    re-exec marker) and anything unknown get the nominal CPU scale."""
    return HW_SPECS.get(str(backend).lower(), HW_SPECS["cpu"])


def floors(hw: HardwareSpec, flops: Optional[float] = None,
           hbm_bytes: Optional[float] = None,
           wire_bytes: Optional[float] = None) -> Dict[str, float]:
    """Per-resource time floors in seconds; resources with no cost number
    (None) are omitted rather than reported as a fake zero floor."""
    out: Dict[str, float] = {}
    if flops is not None and flops > 0:
        out["compute"] = float(flops) / hw.peak_flops
    if hbm_bytes is not None and hbm_bytes > 0:
        out["hbm"] = float(hbm_bytes) / hw.hbm_bytes_per_s
    if wire_bytes is not None and wire_bytes > 0:
        out["ici"] = float(wire_bytes) / hw.ici_bytes_per_s
    return out


def attribute(hw: HardwareSpec, measured_s: Optional[float] = None,
              flops: Optional[float] = None,
              hbm_bytes: Optional[float] = None,
              wire_bytes: Optional[float] = None) -> Dict[str, Any]:
    """One site's attribution row: floors, binding resource, and the
    predicted-vs-measured gap (``measured / max(floor)``; None when either
    side is missing)."""
    fl = floors(hw, flops=flops, hbm_bytes=hbm_bytes, wire_bytes=wire_bytes)
    binding = None
    floor_s = 0.0
    for r in RESOURCES:  # deterministic tie-break in RESOURCES order
        if r in fl and fl[r] > floor_s:
            binding, floor_s = r, fl[r]
    gap = None
    bound_frac = None
    if measured_s is not None and measured_s > 0 and floor_s > 0:
        gap = measured_s / floor_s
        bound_frac = min(1.0, floor_s / measured_s)
    return {
        "floors_ms": {r: round(s * 1e3, 4) for r, s in fl.items()},
        "binding": binding,
        "floor_ms": round(floor_s * 1e3, 4),
        "measured_ms": (round(measured_s * 1e3, 4)
                        if measured_s is not None else None),
        "gap": round(gap, 3) if gap is not None else None,
        "bound_fraction": (round(bound_frac, 3)
                           if bound_frac is not None else None),
        "inputs": {"flops": flops, "hbm_bytes": hbm_bytes,
                   "wire_bytes": wire_bytes},
    }


def train_hbm_bytes_estimate(n_params: int, param_bytes: int = 2,
                             grad_bytes: Optional[int] = None,
                             master: bool = True,
                             moment_bytes: int = 2) -> int:
    """Analytic LOWER BOUND on one optimizer step's HBM traffic from the
    parameter/optimizer working set alone (activations and remat reads are
    deliberately excluded — they depend on batch/remat policy, and a floor
    must not overclaim): params read fwd+bwd, grads written, fp32 master
    read+written when ``master``, two Adam moments read+written, updated
    params written back."""
    n = int(n_params)
    g = param_bytes if grad_bytes is None else grad_bytes
    per_param = (2 * param_bytes          # fwd + bwd param reads
                 + g                      # grad write
                 + (8 if master else 0)   # fp32 master read + write
                 + 4 * moment_bytes       # 2 moments, read + write
                 + param_bytes)           # updated param write
    return n * per_param


def site_report(sites: Mapping[str, Mapping[str, Any]],
                backend: str = "tpu",
                measured: Optional[Mapping[str, float]] = None
                ) -> Dict[str, Any]:
    """Build the AttributionReport for {site: {"flops", "hbm_bytes",
    "wire_bytes", optional "measured_s"}}. ``measured`` (site -> seconds)
    overrides/supplies measured time — the XPlane/goodput reconciliation
    feed."""
    hw = hardware_for_backend(backend)
    rows: Dict[str, Any] = {}
    for name in sorted(sites):
        c = sites[name]
        m = c.get("measured_s")
        if measured is not None and name in measured:
            m = measured[name]
        rows[name] = attribute(
            hw, measured_s=m, flops=c.get("flops"),
            hbm_bytes=c.get("hbm_bytes"), wire_bytes=c.get("wire_bytes"))
    return {"schema": SCHEMA, "backend": backend,
            "hardware": hw.as_dict(), "sites": rows}


def reconcile_sites(perf_sites: Mapping[str, Mapping[str, Any]],
                    hlo_sites: Mapping[str, Mapping[str, Any]],
                    wire_tol: float = WIRE_TOLERANCE,
                    hbm_tol: float = HBM_TOLERANCE) -> List[str]:
    """Cross-check the attribution ledger against the HLO audit ledger
    (``tools/hlo_baseline.json``): every perf site that names wire bytes /
    an HBM peak must agree with the audited truth within tolerance, and
    its FLOPs must be present and positive. Returns human-readable
    mismatch strings; empty means reconciled."""

    def _off(base: float, actual: float, tol: float) -> bool:
        if base == 0:
            return actual != 0
        return abs(actual - base) / base > tol

    problems: List[str] = []
    for name in sorted(perf_sites):
        ps = perf_sites[name]
        hs = hlo_sites.get(name)
        if hs is None:
            problems.append(f"{name}: not in hlo baseline")
            continue
        flops = ps.get("flops")
        if flops is None or (flops <= 0 and not ps.get("hbm_bytes")):
            # zero flops with nonzero bytes-accessed is a real profile (a
            # pure data-movement program, e.g. reshard); zero BOTH means
            # cost_analysis never ran for the site
            problems.append(f"{name}: no cost_analysis flops recorded")
        pw, hw_ = ps.get("wire_bytes"), hs.get("wire_bytes", 0)
        if pw is not None and _off(float(hw_), float(pw), wire_tol):
            problems.append(
                f"{name}: wire_bytes {pw} vs hlo baseline {hw_} "
                f"(> {wire_tol:.0%})")
        pp, hp = ps.get("hbm_peak_bytes"), hs.get("hbm_peak_bytes", 0)
        if pp is not None and _off(float(hp), float(pp), hbm_tol):
            problems.append(
                f"{name}: hbm_peak_bytes {pp} vs hlo baseline {hp} "
                f"(> {hbm_tol:.0%})")
    return problems


def measured_step_seconds(source: Mapping[str, Any]) -> Optional[float]:
    """Portable measured step time from telemetry: the mean of the
    ``train.step.seconds`` histogram when present, else total goodput
    bucket seconds / ``train.steps``. Accepts either a registry snapshot
    (``metrics.snapshot()``) or an ``aggregate.fleet_report`` result."""
    hists = source.get("histograms", {})
    h = hists.get("train.step.seconds")
    if h and h.get("count"):
        return float(h["sum"]) / float(h["count"])
    counters = source.get("counters", {})

    def _val(key: str) -> float:
        v = counters.get(key, 0)
        if isinstance(v, Mapping):  # fleet_report counters: {"total": ...}
            v = v.get("total", 0)
        return float(v or 0)

    goodput = sum(_val(k) for k in counters
                  if k.startswith("train.goodput.seconds"))
    steps = _val("train.steps")
    if goodput > 0 and steps > 0:
        return goodput / steps
    return None


def render(report: Mapping[str, Any]) -> str:
    """Text table of an attribution report."""
    hw = report.get("hardware", {})
    lines = [f"attribution ({report.get('backend')}, {hw.get('name')}: "
             f"{hw.get('peak_flops', 0) / 1e12:.0f} TF/s, "
             f"{hw.get('hbm_bytes_per_s', 0) / 1e9:.0f} GB/s HBM, "
             f"{hw.get('ici_bytes_per_s', 0) / 1e9:.0f} GB/s ICI)", "",
             f"{'site':<28}{'binding':>9}{'floor ms':>12}"
             f"{'measured ms':>13}{'gap':>8}  floors"]
    lines.append("-" * 96)
    for name, row in sorted(report.get("sites", {}).items()):
        fl = " ".join(f"{r}={v:g}" for r, v in row["floors_ms"].items())
        lines.append(
            f"{name[:27]:<28}{str(row['binding']):>9}"
            f"{row['floor_ms']:>12g}"
            f"{('-' if row['measured_ms'] is None else format(row['measured_ms'], 'g')):>13}"
            f"{('-' if row['gap'] is None else format(row['gap'], 'g')):>8}"
            f"  {fl}")
    return "\n".join(lines)


def record_report(report: Mapping[str, Any]) -> None:
    """Flag-gated export of an attribution report into the metrics
    registry (``perf.attribution.*``). Lazily imports the registry so this
    module stays importable standalone (synthetic-package / no-jax hosts:
    the import fails harmlessly and recording is a no-op)."""
    try:
        from . import metrics  # noqa: PLC0415
    except Exception:
        return
    if not metrics.enabled():
        return
    for name, row in report.get("sites", {}).items():
        for r, ms in row["floors_ms"].items():
            metrics.gauge("perf.attribution.floor_ms", ms, site=name,
                          resource=r)
        if row["binding"] is not None:
            metrics.gauge("perf.attribution.bound", 1.0, site=name,
                          resource=row["binding"])
        if row["gap"] is not None:
            metrics.gauge("perf.attribution.gap", row["gap"], site=name)


def load_json(path: str) -> Dict[str, Any]:
    """Tiny helper shared by the report tools (kept here so they stay
    import-light)."""
    with open(path) as f:
        return json.load(f)
