"""XPlane device profiling made reusable: trace collection + op table.

Promoted from the one-off ``tools/xplane_op_profile.py`` (the resnet r4
ceiling-analysis methodology) into a module the roofline attribution tier
(``observability/attribution.py``) can consume: ``collect()`` runs a step
function under ``jax.profiler.trace`` and returns the ``*.xplane.pb``
paths, ``op_table()`` converts them into the per-op self-time table, and
``device_time_seconds()`` reduces that to the measured device step time an
attribution report reconciles its predicted floors against.

Degradation contract (the satellite this module exists for): the XPlane
converter lives in the optional ``xprof`` package, which production CI
hosts do not install. Every entry point here degrades gracefully —
``have_xprof()`` is False, ``op_table()`` returns None instead of raising
ImportError, and callers fall back to the portable measured-time source
(the goodput buckets / ``train.step.seconds`` histogram). Only
``collect()`` needs jax (it drives the profiler); nothing here imports
jax or xprof at module import time.
"""

from __future__ import annotations

import glob
import json
import tempfile
from typing import Any, Dict, List, Optional, Sequence

try:
    from . import metrics as _metrics
except ImportError:  # synthetic-package hosts (tools/anatomy_report.py):
    # metrics drags in core.flags, which is not stdlib-standalone — the
    # counters here are advisory, so degrade to a no-op sink
    class _NullMetrics:
        @staticmethod
        def counter(*args, **kwargs):
            return None

    _metrics = _NullMetrics()  # type: ignore[assignment]

#: the xprof tool name whose converted output is the per-op stats table
OP_STATS_TOOL = "framework_op_stats"


def have_xprof() -> bool:
    """True when the optional ``xprof`` converter package is importable."""
    try:
        import importlib.util

        return importlib.util.find_spec("xprof") is not None
    except Exception:
        return False


def _block_until_ready(result) -> None:
    """Block on every array inside ``result``. ``jax.block_until_ready``
    walks pytrees itself, but framework ``Tensor`` wrappers are opaque
    leaves to it — unwrap ``._value`` per leaf so a tuple of Tensors
    blocks on every member instead of silently skipping them all."""
    import jax

    leaves = jax.tree_util.tree_leaves(result)
    jax.block_until_ready([getattr(leaf, "_value", leaf) for leaf in leaves])


def collect(step_fn, *args, iters: int = 3,
            trace_dir: Optional[str] = None) -> List[str]:
    """Run ``step_fn(*args)`` ``iters`` times under ``jax.profiler.trace``
    (one warm call first, outside the trace, so compile time never pollutes
    the op table) and return the produced ``*.xplane.pb`` paths."""
    import jax

    _block_until_ready(step_fn(*args))  # compile outside the trace
    d = trace_dir or tempfile.mkdtemp(prefix="xplane_")
    with jax.profiler.trace(d):
        r = None
        for _ in range(iters):
            r = step_fn(*args)
        _block_until_ready(r)
    paths = glob.glob(d + "/**/*.xplane.pb", recursive=True)
    _metrics.counter("perf.xplane.collections", 1)
    return paths


def op_table(xplane_paths: Sequence[str],
             tool: str = OP_STATS_TOOL) -> Optional[str]:
    """Convert XPlane protos into the named tool's data (a JSON string for
    ``framework_op_stats``). Returns None — degrading gracefully — when
    ``xprof`` is not installed or the paths are empty."""
    if not xplane_paths:
        return None
    try:
        from xprof.convert import raw_to_tool_data
    except ImportError:
        _metrics.counter("perf.xplane.no_xprof", 1)
        return None
    data, _ = raw_to_tool_data.xspace_to_tool_data(
        list(xplane_paths), tool, {})
    return data if isinstance(data, str) else data.decode()


def op_rows(table: Optional[str]) -> List[Dict[str, Any]]:
    """Parse an ``op_table()`` result into row dicts. Handles both a plain
    list of records and the gviz DataTable shape ({"cols": [...], "rows":
    [{"c": [{"v": ...}]}]}) xprof's converters emit; returns [] on any
    shape it does not recognize (the table is advisory, never gating)."""
    if not table:
        return []
    try:
        data = json.loads(table)
    except (json.JSONDecodeError, TypeError):
        return []
    if isinstance(data, list) and all(isinstance(r, dict) for r in data):
        return data
    if isinstance(data, dict) and "rows" in data and "cols" in data:
        labels = [c.get("label") or c.get("id") or f"col{i}"
                  for i, c in enumerate(data["cols"])]
        rows = []
        for r in data["rows"]:
            cells = r.get("c") or []
            rows.append({labels[i]: (cell or {}).get("v")
                         for i, cell in enumerate(cells)
                         if i < len(labels)})
        return rows
    return []


def _self_time_key(row: Dict[str, Any]) -> Optional[str]:
    for k in row:
        lk = str(k).lower()
        if "self" in lk and "time" in lk and "%" not in lk:
            return k
    return None


def self_time_key(rows: List[Dict[str, Any]]) -> Optional[str]:
    """The self-time column name, scanning every row until one carries it —
    gviz rows with null leading cells must not blind the whole table."""
    for row in rows:
        key = _self_time_key(row)
        if key is not None:
            return key
    return None


def top_ops(rows: List[Dict[str, Any]], n: int = 10) -> List[Dict[str, Any]]:
    """The ``n`` largest rows by self time (row order preserved when no
    self-time column is recognizable)."""
    if not rows:
        return []
    key = self_time_key(rows)
    if key is None:
        return rows[:n]
    return sorted(rows, key=lambda r: float(r.get(key) or 0.0),
                  reverse=True)[:n]


def device_time_seconds(rows: List[Dict[str, Any]],
                        iters: int = 1) -> Optional[float]:
    """Total device self time per iteration, in seconds (op-stats report
    microseconds). None when the rows carry no recognizable self-time
    column — callers then fall back to goodput-bucket measured time."""
    if not rows:
        return None
    key = self_time_key(rows)
    if key is None:
        return None
    total_us = 0.0
    for r in rows:
        try:
            total_us += float(r.get(key) or 0.0)
        except (TypeError, ValueError):
            continue
    return total_us * 1e-6 / max(int(iters), 1)


def measure(step_fn, *args, iters: int = 3) -> Dict[str, Any]:
    """collect + convert + reduce in one call: {"xplane_paths", "available",
    "rows", "device_time_s"}. ``available`` is False (and the measured
    fields None/[]) when xprof is absent — the caller keeps its portable
    fallback; the trace files are still on disk for offline conversion."""
    paths = collect(step_fn, *args, iters=iters)
    table = op_table(paths)
    rows = op_rows(table)
    return {
        "xplane_paths": paths,
        "available": table is not None,
        "rows": rows,
        "device_time_s": device_time_seconds(rows, iters=iters),
    }
