"""Step-anatomy tier: per-scope time attribution for the training step.

The roofline tier (``attribution.py``, PR 11) answers *whether* a step
runs above its floor; this tier answers *which scope owns the gap* — the
missing input for sharding auto-search. The contract has three layers:

1. **Scope naming convention** — the model and training stack annotate
   themselves with ``jax.named_scope`` using a stable vocabulary
   (``block_NN/attn``, ``block_NN/mlp``, ``block_NN/moe``, ``embed``,
   ``final_ln``, ``loss``, ``opt/update``, ``comm/grad_reduce``,
   ``serving/prefill``, ``serving/decode``). The names survive into HLO
   op metadata (and into ``eqn.source_info.name_stack`` at trace time),
   wrapped in transform frames (``jvp(...)``/``transpose(...)``) that
   :func:`clean_scope_path` strips.

2. **Per-scope cost split** — :func:`scope_costs` walks a step jaxpr
   (including nested scan/remat/pjit bodies, whose name stacks are
   *relative* to the enclosing equation) and accumulates flops, HBM
   bytes, and explicit-collective wire bytes per canonical scope;
   :func:`wire_from_flow` merges GSPMD-implicit wire predicted by
   ``analysis.sharding_flow`` FlowEvents (which carry a ``scope`` field).
   :func:`attribution.floors` turns each scope's costs into time floors.

3. **Gap table** — :func:`report` joins the floors against measured
   per-scope self time from ``xplane.op_rows()`` (when xprof is
   installed) and emits the sorted measured-minus-floor table. Without
   xprof the same report lands with ``measured_ms: null`` per scope —
   the static-only degradation path, same contract as
   ``xplane.have_xprof()``.

Stdlib-only at import time (the synthetic-package contract shared with
``attribution.py``): ``tools/anatomy_report.py`` renders reports on
hosts with no jax. Only :func:`scope_costs` touches jax, lazily.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from . import attribution
from . import xplane

SCHEMA = "paddle_tpu.anatomy.v1"

#: the catch-all bucket for device work outside every annotated scope;
#: budgeted at <5% of step time in the bench row (scope-coverage lint)
UNATTRIBUTED = "unattributed"
UNATTRIBUTED_BUDGET = 0.05

#: Σ per-scope floors must land within this of the whole-step floor
FLOOR_SUM_TOLERANCE = 0.10

#: recognized sub-scopes inside a transformer block
BLOCK_SUBSCOPES = ("attn", "mlp", "moe")
#: roots whose canonical scope keeps two path components (opt/update,
#: comm/grad_reduce, serving/prefill|decode, obs/…, data/…)
TWO_LEVEL_ROOTS = ("opt", "comm", "serving", "obs", "data")
#: roots whose canonical scope is the single component
SINGLE_ROOTS = ("embed", "final_ln", "loss")

_BLOCK_RE = re.compile(r"^block_(\d+)$")
#: transform frames jax wraps around scope names: ``jvp(block_00)``,
#: ``transpose(jvp(block_00))``, ``jit(step)``, ``remat(...)``
_TRANSFORM_CALL_RE = re.compile(r"[A-Za-z0-9_.\-]+\(")
_GROUP_LAYER_RE = re.compile(r"\.layers?\.(\d+)$")


# -- scope naming ----------------------------------------------------------

def clean_scope_path(raw: Any) -> str:
    """Strip jax transform frames from a name-stack/op-name string:
    ``transpose(jvp(block_00))/mlp`` -> ``block_00/mlp``."""
    s = _TRANSFORM_CALL_RE.sub("", str(raw or "")).replace(")", "")
    return "/".join(p for p in s.split("/") if p)


def scope_of_path(path: Any) -> str:
    """The canonical scope a raw scope path / HLO op name belongs to.

    Scans the cleaned path components for the first recognized scope
    root (skipping transform artifacts like ``jit``/``step``):
    ``block_\\d+`` keeps its first recognized sub-scope
    (``block_03/mlp``), two-level roots keep the next component
    (``opt/update``), single roots stand alone (``loss``). Anything
    without a recognized root lands in :data:`UNATTRIBUTED`.
    """
    parts = clean_scope_path(path).split("/")
    for i, comp in enumerate(parts):
        m = _BLOCK_RE.match(comp)
        if m:
            base = "block_%02d" % int(m.group(1))
            sub = next((p for p in parts[i + 1:] if p in BLOCK_SUBSCOPES),
                       None)
            return f"{base}/{sub}" if sub else base
        if comp in TWO_LEVEL_ROOTS:
            if i + 1 < len(parts):
                return f"{comp}/{parts[i + 1]}"
            return comp
        if comp in SINGLE_ROOTS:
            return comp
    return UNATTRIBUTED


def scope_for_param_group(group: str) -> Optional[str]:
    """Map a ``health.param_group()`` name onto its anatomy scope
    (``gpt.layers.3`` -> ``block_03``); None when the group has no
    annotated scope — the scope-coverage lint fails on those."""
    m = _GROUP_LAYER_RE.search(group)
    if m:
        return "block_%02d" % int(m.group(1))
    leaf = group.split(".")[-1]
    if leaf in ("embeddings", "embedding", "embed", "word_embeddings",
                "position_embeddings"):
        return "embed"
    if leaf in ("final_ln", "ln_f", "final_layernorm", "final_norm"):
        return "final_ln"
    return None


# -- per-scope cost split (jax only here, lazily) --------------------------

#: explicit cross-chip collectives a jaxpr can carry (the shard_map /
#: manual-mesh path); GSPMD-implicit wire comes from sharding_flow events
_COLLECTIVE_FACTORS = {
    # all-reduce moves ~2·(n-1)/n of the buffer per chip (ring)
    "psum": lambda n: 2.0 * (n - 1) / n,
    "pmin": lambda n: 2.0 * (n - 1) / n,
    "pmax": lambda n: 2.0 * (n - 1) / n,
    # gather/scatter move (n-1)/n shards of the full buffer
    "all_gather": lambda n: float(n - 1) / n,
    "reduce_scatter": lambda n: float(n - 1) / n,
    "all_to_all": lambda n: float(n - 1) / n,
    "ppermute": lambda n: 1.0 if n > 1 else 0.0,
}


def _aval_bytes(aval: Any) -> int:
    size = 1
    for d in getattr(aval, "shape", ()):
        try:
            size *= int(d)
        except (TypeError, ValueError):
            return 0
    itemsize = getattr(getattr(aval, "dtype", None), "itemsize", None)
    return size * int(itemsize) if itemsize else 0


def _prod(it: Iterable[int]) -> int:
    out = 1
    for x in it:
        out *= int(x)
    return out


def _dot_flops(eqn: Any) -> float:
    """2·batch·M·N·K for a ``dot_general`` from its dimension numbers."""
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    ls = eqn.invars[0].aval.shape
    rs = eqn.invars[1].aval.shape
    batch = _prod(ls[d] for d in lb)
    k = _prod(ls[d] for d in lc)
    m = _prod(ls[d] for d in range(len(ls)) if d not in lc and d not in lb)
    n = _prod(rs[d] for d in range(len(rs)) if d not in rc and d not in rb)
    return 2.0 * batch * m * n * k


def _axis_product(params: Mapping[str, Any],
                  axis_sizes: Mapping[str, int]) -> int:
    names = params.get("axes") or params.get("axis_name") or ()
    if not isinstance(names, (tuple, list)):
        names = (names,)
    return _prod(axis_sizes.get(a, 1) for a in names) or 1


def _eqn_costs(eqn: Any, axis_sizes: Mapping[str, int]
               ) -> Tuple[float, float, float]:
    """(flops, hbm_bytes, wire_bytes) for one leaf equation. The flops
    model counts MXU work (dot_general) only — elementwise flops are
    bandwidth-shadowed and would just add noise to compute floors; every
    equation's operand+result bytes count toward the HBM floor."""
    in_b = sum(_aval_bytes(v.aval) for v in eqn.invars
               if hasattr(v, "aval"))
    out_b = sum(_aval_bytes(v.aval) for v in eqn.outvars
                if hasattr(v, "aval"))
    prim = eqn.primitive.name
    flops = _dot_flops(eqn) if prim == "dot_general" else 0.0
    wire = 0.0
    factor = _COLLECTIVE_FACTORS.get(prim)
    if factor is not None:
        n = _axis_product(eqn.params, axis_sizes)
        if n > 1:
            wire = in_b * factor(n)
    return flops, float(in_b + out_b), wire


def _sub_jaxprs(eqn: Any) -> List[Tuple[Any, int]]:
    """(inner jaxpr, iteration multiplier) pairs for a higher-order
    equation; [] for leaves. remat2 carries a raw Jaxpr where pjit/scan
    carry a ClosedJaxpr — ``getattr(item, "jaxpr", item)`` covers both."""
    prim = eqn.primitive.name
    params = eqn.params
    if prim == "scan":
        inner = getattr(params["jaxpr"], "jaxpr", params["jaxpr"])
        return [(inner, int(params.get("length") or 1))]
    if prim == "while":
        # trip count is dynamic; one iteration is the honest static floor
        return [(getattr(params[k], "jaxpr", params[k]), 1)
                for k in ("cond_jaxpr", "body_jaxpr") if k in params]
    if prim == "cond":
        return [(getattr(b, "jaxpr", b), 1)
                for b in params.get("branches", ())]
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in params:
            inner = getattr(params[key], "jaxpr", params[key])
            if hasattr(inner, "eqns"):
                return [(inner, 1)]
    return []


def _zero() -> Dict[str, float]:
    return {"flops": 0.0, "hbm_bytes": 0.0, "wire_bytes": 0.0}


def scope_costs(jaxpr: Any,
                axis_sizes: Optional[Mapping[str, int]] = None
                ) -> Dict[str, Dict[str, float]]:
    """Walk a (closed) jaxpr and split costs per canonical scope.

    Nested jaxprs (scan/remat/pjit bodies) carry name stacks *relative*
    to their enclosing equation, so the walker threads the enclosing
    equation's cleaned scope path down as a prefix; scan bodies multiply
    by the trace-time ``length``.
    """
    sizes = dict(axis_sizes or {})
    costs: Dict[str, Dict[str, float]] = {}

    def add(scope: str, f: float, h: float, w: float, mult: int) -> None:
        d = costs.setdefault(scope, _zero())
        d["flops"] += f * mult
        d["hbm_bytes"] += h * mult
        d["wire_bytes"] += w * mult

    def walk(jx: Any, prefix: str, mult: int) -> None:
        for eqn in jx.eqns:
            stack = clean_scope_path(
                getattr(eqn.source_info, "name_stack", ""))
            full = f"{prefix}/{stack}" if prefix and stack else (
                stack or prefix)
            subs = _sub_jaxprs(eqn)
            if subs:
                # inner equations carry the bytes; counting the call
                # frame's operands too would double every boundary
                for sub, m in subs:
                    walk(sub, full, mult * m)
                continue
            f, h, w = _eqn_costs(eqn, sizes)
            add(scope_of_path(full), f, h, w, mult)

    walk(getattr(jaxpr, "jaxpr", jaxpr), "", 1)
    return costs


def flat_costs(jaxpr: Any,
               axis_sizes: Optional[Mapping[str, int]] = None
               ) -> Dict[str, float]:
    """Whole-step cost totals from an independent scope-blind walk — the
    reconciliation reference :func:`report` checks the per-scope split
    against (a split that dropped equations cannot sum back to this)."""
    sizes = dict(axis_sizes or {})
    total = _zero()

    def walk(jx: Any, mult: int) -> None:
        for eqn in jx.eqns:
            subs = _sub_jaxprs(eqn)
            if subs:
                for sub, m in subs:
                    walk(sub, mult * m)
                continue
            f, h, w = _eqn_costs(eqn, sizes)
            total["flops"] += f * mult
            total["hbm_bytes"] += h * mult
            total["wire_bytes"] += w * mult

    walk(getattr(jaxpr, "jaxpr", jaxpr), 1)
    return total


def wire_from_flow(events: Iterable[Any],
                   costs: Optional[Dict[str, Dict[str, float]]] = None
                   ) -> Dict[str, Dict[str, float]]:
    """Merge sharding_flow FlowEvents' predicted wire bytes into a
    per-scope cost table (GSPMD inserts these collectives after tracing,
    so the jaxpr walker can never see them). Events carry the ``scope``
    field sharding_flow threads from the same name stacks."""
    out = {k: dict(v) for k, v in (costs or {}).items()}
    for ev in events:
        kind = getattr(ev, "kind", "")
        if not kind.startswith(("all-", "reduce-", "point-to-point")):
            continue
        scope = scope_of_path(getattr(ev, "scope", "") or
                              getattr(ev, "path", ""))
        d = out.setdefault(scope, _zero())
        d["wire_bytes"] += float(getattr(ev, "nbytes", 0) or 0)
    return out


# -- measured self time per scope ------------------------------------------

def _op_name_key(row: Mapping[str, Any]) -> Optional[str]:
    for k in row:
        lk = str(k).lower().replace(" ", "_")
        if lk in ("op_name", "name", "operation", "operation_name"):
            return k
    return None


def measured_by_scope(rows: List[Dict[str, Any]],
                      iters: int = 1) -> Dict[str, float]:
    """Aggregate ``xplane.op_rows()`` self time (microseconds) per scope,
    in seconds per iteration. {} when the rows carry no recognizable
    op-name or self-time column (static-only path takes over)."""
    tkey = xplane.self_time_key(rows)
    nkey = None
    for row in rows:
        nkey = _op_name_key(row)
        if nkey is not None:
            break
    if tkey is None or nkey is None:
        return {}
    out: Dict[str, float] = {}
    for r in rows:
        try:
            us = float(r.get(tkey) or 0.0)
        except (TypeError, ValueError):
            continue
        scope = scope_of_path(str(r.get(nkey) or ""))
        out[scope] = out.get(scope, 0.0) + us
    return {k: v * 1e-6 / max(int(iters), 1) for k, v in out.items()}


# -- the gap-attribution report --------------------------------------------

def report(hw: "attribution.HardwareSpec",
           costs: Mapping[str, Mapping[str, float]],
           measured: Optional[Mapping[str, float]] = None,
           flat: Optional[Mapping[str, float]] = None) -> Dict[str, Any]:
    """Join per-scope floors with (optional) measured self time into the
    gap-attribution table. ``measured`` maps scope -> seconds; None is
    the static-only path — every ``measured_ms``/``gap_ms`` is null and
    rows sort by floor instead of gap. ``flat`` (scope-blind totals)
    drives the Σ-floors-vs-whole-step reconciliation."""
    measured = dict(measured or {})
    rows: List[Dict[str, Any]] = []
    for scope in sorted(costs):
        c = costs[scope]
        row = attribution.attribute(
            hw, measured_s=measured.get(scope),
            flops=c.get("flops") or None,
            hbm_bytes=c.get("hbm_bytes") or None,
            wire_bytes=c.get("wire_bytes") or None)
        row["scope"] = scope
        row["gap_ms"] = (round(row["measured_ms"] - row["floor_ms"], 4)
                         if row["measured_ms"] is not None else None)
        rows.append(row)
    have_measured = any(r["measured_ms"] is not None for r in rows)
    if have_measured:
        rows.sort(key=lambda r: (r["gap_ms"] is None,
                                 -(r["gap_ms"] or 0.0), r["scope"]))
    else:
        rows.sort(key=lambda r: (-r["floor_ms"], r["scope"]))

    floor_sum_ms = round(sum(r["floor_ms"] for r in rows), 4)
    measured_sum_ms = (round(sum(r["measured_ms"] or 0.0 for r in rows), 4)
                       if have_measured else None)
    flat = dict(flat) if flat else {
        k: sum(c.get(k, 0.0) for c in costs.values())
        for k in ("flops", "hbm_bytes", "wire_bytes")}
    whole = attribution.attribute(
        hw, measured_s=None, flops=flat.get("flops") or None,
        hbm_bytes=flat.get("hbm_bytes") or None,
        wire_bytes=flat.get("wire_bytes") or None)
    ratio = (round(floor_sum_ms / whole["floor_ms"], 4)
             if whole["floor_ms"] else None)

    # the unattributed bucket's share of step time: measured share when a
    # profile exists, floor share on the static-only path
    share_of = ("measured_ms" if have_measured else "floor_ms")
    total_share = sum(r[share_of] or 0.0 for r in rows)
    unattr = next((r for r in rows if r["scope"] == UNATTRIBUTED), None)
    unattributed_fraction = (
        round((unattr[share_of] or 0.0) / total_share, 4)
        if unattr and total_share else 0.0)

    return {
        "schema": SCHEMA,
        "hardware": hw.as_dict(),
        "measured": have_measured,
        "scopes": rows,
        "whole_step": whole,
        "totals": {
            "floor_sum_ms": floor_sum_ms,
            "measured_sum_ms": measured_sum_ms,
            "whole_floor_ms": whole["floor_ms"],
            "floor_sum_ratio": ratio,
            "floor_sum_ok": (ratio is not None and
                             abs(ratio - 1.0) <= FLOOR_SUM_TOLERANCE),
            "unattributed_fraction": unattributed_fraction,
            "unattributed_ok":
                unattributed_fraction < UNATTRIBUTED_BUDGET,
        },
    }


def top_gap_scope(rep: Mapping[str, Any]) -> Optional[str]:
    """The scope owning the largest measured-minus-floor gap (falls back
    to the largest floor on the static-only path)."""
    rows = rep.get("scopes") or []
    if not rows:
        return None
    if rep.get("measured"):
        best = max(rows, key=lambda r: (r.get("gap_ms") or float("-inf")))
    else:
        best = max(rows, key=lambda r: r.get("floor_ms") or 0.0)
    return best.get("scope")


def render(rep: Mapping[str, Any]) -> str:
    """Text table of a report (the CLI and bench --verbose share this)."""
    hw = rep.get("hardware", {})
    lines = [
        "step anatomy (%s)%s" % (
            hw.get("name", "?"),
            "" if rep.get("measured") else
            "  [static-only: no xprof, measured column absent]"),
        "%-22s %-8s %10s %10s %10s" % (
            "scope", "bound", "floor_ms", "meas_ms", "gap_ms"),
    ]
    for r in rep.get("scopes", []):
        lines.append("%-22s %-8s %10.4f %10s %10s" % (
            r["scope"], r.get("binding") or "-", r["floor_ms"],
            "-" if r["measured_ms"] is None else "%.4f" % r["measured_ms"],
            "-" if r["gap_ms"] is None else "%+.4f" % r["gap_ms"]))
    t = rep.get("totals", {})
    lines.append(
        "Σ floors %.4f ms vs whole-step floor %.4f ms (ratio %s, %s); "
        "unattributed %.2f%% (%s)" % (
            t.get("floor_sum_ms", 0.0), t.get("whole_floor_ms", 0.0),
            t.get("floor_sum_ratio"),
            "ok" if t.get("floor_sum_ok") else "OUT OF TOLERANCE",
            100.0 * (t.get("unattributed_fraction") or 0.0),
            "ok" if t.get("unattributed_ok") else "over budget"))
    return "\n".join(lines)


def record_report(rep: Mapping[str, Any]) -> None:
    """Flag-gated export into the metrics registry (``perf.anatomy.*``)
    plus a flight-recorder snapshot. Lazy imports keep the module
    importable standalone; a dead registry makes this a no-op."""
    try:
        from . import metrics
    except Exception:
        return
    if not metrics.enabled():
        return
    metrics.counter("perf.anatomy.reports", 1)
    for r in rep.get("scopes", []):
        metrics.gauge("perf.anatomy.floor_ms", r["floor_ms"],
                      scope=r["scope"])
        if r["measured_ms"] is not None:
            metrics.gauge("perf.anatomy.measured_ms", r["measured_ms"],
                          scope=r["scope"])
        if r["gap_ms"] is not None:
            metrics.gauge("perf.anatomy.gap_ms", r["gap_ms"],
                          scope=r["scope"])
    t = rep.get("totals", {})
    if t.get("floor_sum_ratio") is not None:
        metrics.gauge("perf.anatomy.floor_sum_ratio",
                      t["floor_sum_ratio"])
    metrics.gauge("perf.anatomy.unattributed_fraction",
                  t.get("unattributed_fraction") or 0.0)
    try:
        from .flight_recorder import record_event
        record_event({"kind": "anatomy", "schema": rep.get("schema"),
                      "totals": dict(t),
                      "top_gap_scope": top_gap_scope(rep)})
    except Exception:
        pass


# -- offline loaders (the no-jax CLI renders from these) -------------------

def report_from_obj(obj: Any) -> Optional[Dict[str, Any]]:
    """Recover a report from parsed JSON: a report itself, a bench row
    carrying one under ``"anatomy"``, or a list of either."""
    if isinstance(obj, Mapping):
        if obj.get("schema") == SCHEMA:
            return dict(obj)
        inner = obj.get("anatomy")
        if isinstance(inner, Mapping) and inner.get("schema") == SCHEMA:
            return dict(inner)
        return None
    if isinstance(obj, list):
        for item in reversed(obj):
            rep = report_from_obj(item)
            if rep is not None:
                return rep
    return None


def report_from_jsonl(path: str) -> Optional[Dict[str, Any]]:
    """Last recoverable report from a JSON/JSONL file (bench rows,
    flight-recorder files, or a bare report dump)."""
    found = None
    with open(path) as f:
        text = f.read()
    try:
        found = report_from_obj(json.loads(text))
        if found is not None:
            return found
    except json.JSONDecodeError:
        pass
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rep = report_from_obj(json.loads(line))
        except json.JSONDecodeError:
            continue
        if rep is not None:
            found = rep
    return found


def report_from_metrics_dump(paths: Iterable[str]) -> Optional[Dict[str, Any]]:
    """Rebuild a (floors/measured/gap only) report from ``perf.anatomy.*``
    gauges in ``metrics.dump_jsonl`` files. Cost inputs are not exported,
    so the rebuilt rows carry times only — enough for the table."""
    floors: Dict[str, float] = {}
    meas: Dict[str, float] = {}
    gaps: Dict[str, float] = {}
    totals: Dict[str, Any] = {}
    seen = False
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                name = rec.get("name", "")
                if not name.startswith("perf.anatomy."):
                    continue
                seen = True
                scope = (rec.get("labels") or {}).get("scope")
                val = rec.get("value")
                if name.endswith(".floor_ms") and scope:
                    floors[scope] = val
                elif name.endswith(".measured_ms") and scope:
                    meas[scope] = val
                elif name.endswith(".gap_ms") and scope:
                    gaps[scope] = val
                elif name.endswith(".floor_sum_ratio"):
                    totals["floor_sum_ratio"] = val
                elif name.endswith(".unattributed_fraction"):
                    totals["unattributed_fraction"] = val
    if not seen:
        return None
    rows = []
    for scope in sorted(floors):
        rows.append({
            "scope": scope, "binding": None, "floors_ms": {},
            "floor_ms": floors[scope],
            "measured_ms": meas.get(scope),
            "gap_ms": gaps.get(scope),
        })
    have_measured = any(r["measured_ms"] is not None for r in rows)
    if have_measured:
        rows.sort(key=lambda r: (r["gap_ms"] is None,
                                 -(r["gap_ms"] or 0.0), r["scope"]))
    else:
        rows.sort(key=lambda r: (-r["floor_ms"], r["scope"]))
    totals.setdefault("floor_sum_ms",
                      round(sum(r["floor_ms"] for r in rows), 4))
    totals.setdefault("whole_floor_ms", 0.0)
    totals.setdefault("floor_sum_ok", True)
    totals.setdefault("unattributed_fraction", 0.0)
    totals.setdefault(
        "unattributed_ok",
        totals["unattributed_fraction"] < UNATTRIBUTED_BUDGET)
    return {"schema": SCHEMA, "hardware": {"name": "from-metrics"},
            "measured": have_measured, "scopes": rows,
            "whole_step": None, "totals": totals}
