"""Crash-safe flight recorder: the forensics a dead run leaves behind.

Keeps a bounded in-memory ring of recent telemetry events and continuously
persists it to ONE per-host file via atomic rewrite (tmp + fsync + rename —
the checkpoint COMMIT idea applied to telemetry), then finalizes the file
with a full metric snapshot on SIGTERM, fatal exception, or interpreter
exit. A preempted v5e host therefore always leaves a readable "black box"
with its last ``capacity`` spans and where its counters stood.

File format (JSONL, ``paddle_tpu.flight.v1``):

    {"kind": "header", "schema": "paddle_tpu.flight.v1", "host": 0,
     "pid": ..., "started_ts": ..., "capacity": 512}
    {"kind": "span", "name": "ckpt.save", "ts": <us>, "dur": <us>, ...}
    {"kind": "metrics", "ts": ..., "counters_delta": {...}, "gauges": {...}}
    ...
    {"kind": "final", "ts": ..., "reason": "sigterm" | "fatal" | "atexit"
     | <caller reason>, "snapshot": <full metrics snapshot>}

Span events arrive through ``tracing.add_span_sink`` — every ``span()``
lands in the ring with zero extra instrumentation at call sites. Each
periodic flush also appends a ``metrics`` event carrying counter deltas
since the previous flush plus current gauges, so the ring interleaves
"what ran" with "what moved".

Self-accounting: ``obs.flight.events`` / ``obs.flight.flushes`` /
``obs.flight.finalized`` / ``obs.flight.errors`` counters and an
``obs.flight.flush_seconds`` histogram.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from . import metrics, tracing
from .export import _default_host

SCHEMA = "paddle_tpu.flight.v1"


class FlightRecorder:
    def __init__(self, path: Optional[str] = None, capacity: int = 512,
                 flush_interval_s: float = 5.0, host: Optional[int] = None):
        self.host = _default_host() if host is None else int(host)
        self.path = path or os.path.join(
            tempfile.gettempdir(),
            f"pt-flight-host{self.host:05d}-{os.getpid()}.jsonl")
        self.capacity = int(capacity)
        self.flush_interval_s = float(flush_interval_s)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._finalized = False
        self._final_event: Optional[Dict[str, Any]] = None
        self._last_counters: Dict[str, float] = {}
        self._started_ts = time.time()
        self._prev_sigterm = None
        self._prev_excepthook = None

    # -- event intake --
    def _on_span(self, event: Dict[str, Any]):
        with self._lock:
            self._ring.append({"kind": "span", **event})
        metrics.counter("obs.flight.events", 1, kind="span")

    def record(self, event: Dict[str, Any]):
        """Public intake for structured one-off events (the serving SLO
        monitor drops per-request violation traces here). ``event`` should
        carry a ``kind``; it lands in the ring like any span and persists
        on the next flush/finalize."""
        ev = dict(event)
        ev.setdefault("kind", "event")
        ev.setdefault("ts", time.time())
        with self._lock:
            self._ring.append(ev)
        metrics.counter("obs.flight.events", 1, kind=ev["kind"])

    def _metrics_event(self) -> Dict[str, Any]:
        snap = metrics.snapshot()
        deltas = {}
        for k, v in snap["counters"].items():
            d = v - self._last_counters.get(k, 0)
            if d:
                deltas[k] = d
        self._last_counters = dict(snap["counters"])
        return {"kind": "metrics", "ts": time.time(),
                "counters_delta": deltas, "gauges": snap["gauges"]}

    # -- persistence: atomic rewrite so a crash mid-flush never corrupts --
    def _write(self, extra: Optional[Dict[str, Any]] = None):
        header = {"kind": "header", "schema": SCHEMA, "host": self.host,
                  "pid": os.getpid(), "started_ts": self._started_ts,
                  "capacity": self.capacity}
        with self._lock:
            events = list(self._ring)
        lines = [header] + events
        if self._final_event is not None:
            lines.append(self._final_event)
        elif extra is not None:
            lines.append(extra)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for ev in lines:
                f.write(json.dumps(ev) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def flush(self) -> Optional[str]:
        if self._finalized:
            return self.path
        t0 = time.perf_counter()
        try:
            ev = self._metrics_event()
            with self._lock:
                self._ring.append(ev)
            self._write()
        except Exception:
            metrics.counter("obs.flight.errors", 1)
            return None
        metrics.counter("obs.flight.flushes", 1)
        metrics.histogram("obs.flight.flush_seconds",
                          time.perf_counter() - t0)
        return self.path

    def finalize(self, reason: str = "atexit") -> Optional[str]:
        """Append the terminal record (full snapshot) and persist.
        Idempotent: the first reason wins; later calls are no-ops."""
        if self._finalized:
            return self.path
        self._finalized = True
        metrics.counter("obs.flight.finalized", 1, reason=reason)
        try:
            self._final_event = {"kind": "final", "ts": time.time(),
                                 "reason": reason,
                                 "snapshot": metrics.snapshot()}
            self._write()
        except Exception:
            return None
        return self.path

    # -- lifecycle + crash hooks --
    def _run(self):
        while not self._stop.wait(self.flush_interval_s):
            self.flush()

    def _on_sigterm(self, signum, frame):
        self.finalize("sigterm")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        else:
            # preserve kill-by-SIGTERM semantics (exit status 143)
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def _on_fatal(self, exc_type, exc, tb):
        self.finalize("fatal")
        hook = self._prev_excepthook or sys.__excepthook__
        hook(exc_type, exc, tb)

    def start(self) -> "FlightRecorder":
        tracing.add_span_sink(self._on_span)
        try:
            if threading.current_thread() is threading.main_thread():
                self._prev_sigterm = signal.signal(signal.SIGTERM,
                                                   self._on_sigterm)
        except (ValueError, OSError):
            self._prev_sigterm = None
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._on_fatal
        atexit.register(self.finalize, "atexit")
        self.flush()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pt-flight-recorder", daemon=True)
        self._thread.start()
        return self

    def stop(self, reason: str = "stop"):
        """Detach hooks, stop the flusher, finalize the file."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        tracing.remove_span_sink(self._on_span)
        try:
            if (self._prev_sigterm is not None
                    and threading.current_thread()
                    is threading.main_thread()):
                signal.signal(signal.SIGTERM, self._prev_sigterm)
        except (ValueError, OSError):
            pass
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        self.finalize(reason)


_recorder: Optional[FlightRecorder] = None


def start_flight_recorder(path: Optional[str] = None, capacity: int = 512,
                          flush_interval_s: float = 5.0,
                          host: Optional[int] = None
                          ) -> Optional[FlightRecorder]:
    """Start (or replace) this process's flight recorder. Returns None —
    starting nothing — when observability is off."""
    global _recorder
    if not metrics.enabled():
        return None
    if _recorder is not None:
        _recorder.stop(reason="replaced")
    _recorder = FlightRecorder(path, capacity, flush_interval_s, host).start()
    return _recorder


def stop_flight_recorder(reason: str = "stop"):
    global _recorder
    if _recorder is not None:
        _recorder.stop(reason=reason)
        _recorder = None


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _recorder


def record_event(event: Dict[str, Any]) -> bool:
    """Drop one structured event into the live recorder's ring; False (a
    no-op) when no recorder is running — callers never need to gate."""
    r = _recorder
    if r is None:
        return False
    r.record(event)
    return True


def read_flight(path: str) -> Dict[str, Any]:
    """Parse a flight-recorder file into {header, events, final} (events
    excludes the header and final records). Tolerates a torn final line."""
    header, final, events = None, None, []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            kind = ev.get("kind")
            if kind == "header":
                header = ev
            elif kind == "final":
                final = ev
            else:
                events.append(ev)
    return {"header": header, "events": events, "final": final}
