"""Training-numerics health: in-graph stat pass + host-side detectors.

The observability tier explains where time goes; this module watches
whether the model is HEALTHY. Two halves:

- ``in_graph_stats`` — a fused reduction computed INSIDE the compiled
  train step (ShardedTrainStep wires it behind ``FLAGS_health_stats``):
  per-param-group grad norm, param norm, update norm, and nonfinite
  counts ride out of the step as a small replicated pytree next to the
  loss. Per GSPMD the reductions partition under the step's own sharding,
  so the monitor costs fused reduce ops, not host round-trips — the
  capability the reference ships as FLAGS_check_nan_inf/nan_inf_utils,
  rebuilt without per-op host checks.
- ``HealthMonitor`` — host-side consumer: EWMA/z-score loss-spike and
  grad-norm-spike detectors, a nonfinite-provenance resolver that names
  the FIRST param group to go NaN/Inf (loss-scaler backoffs are
  attributed to it instead of being silently eaten), loss-scale event
  tracking, ``health.*`` metrics, and forensic capture — each anomaly is
  recorded to the flight recorder with the full per-group stat table and
  the offending batch's ``data_position``.

Wiring (see examples/gpt_pretrain.py --health)::

    step = make_sharded_train_step(model, opt, health_stats=True)
    mon = step.attach_health_monitor(HealthMonitor(
        on_anomaly=print, data_position=pipe.get_state))
    for x, y in batches:
        loss = step(x, y)      # stats observed one step later (no stall)
    step.health_flush()        # deliver the final step's stats
    print(mon.summary())

Imports of jax and the metrics registry are lazy: detectors and parsing
stay importable from the no-jax tools (health_report.py) via the same
synthetic-package trick as aggregate.py.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

SCHEMA = "paddle_tpu.health.v1"
GLOBAL_GROUP = "_global"

ANOMALY_KINDS = ("nonfinite", "loss_nonfinite", "loss_spike",
                 "grad_norm_spike", "overflow_skip")


def _metrics():
    """The metrics registry, or None outside the package (no-jax tools)."""
    try:
        from . import metrics
        return metrics
    except Exception:
        return None


def _flight():
    try:
        from . import flight_recorder
        return flight_recorder
    except Exception:
        return None


def stats_enabled() -> bool:
    """FLAGS_health_stats — gates the in-graph stat pass (default off, so
    the analyzer corpus / HLO baselines see the unchanged step)."""
    try:
        from ..core.flags import flag_value
        return bool(flag_value("health_stats"))
    except Exception:
        return False


# ---------------------------------------------------------------------------
# param grouping
# ---------------------------------------------------------------------------

def param_group(name: str) -> str:
    """Top-level group of a dotted param name.

    The leaf component (weight/bias/...) is dropped, then the group is the
    prefix up to and including the first numeric component — so every
    param of one transformer block lands in one group
    (``gpt.layers.0.attn.qkv.weight`` -> ``gpt.layers.0``) — else the
    first two components (``gpt.embeddings``, ``gpt.final_ln``). Handles
    pipeline-stacked names (``...__stacked__...`` has no numeric layer
    index: the whole stack is one group).
    """
    parts = name.split(".")
    base = parts[:-1] if len(parts) > 1 else parts
    for i, comp in enumerate(base):
        if comp.isdigit():
            return ".".join(base[: i + 1])
    return ".".join(base[:2]) if len(base) >= 2 else base[0]


def group_index_map(names: Sequence[str],
                    group_fn: Callable[[str], str] = param_group,
                    ) -> Tuple[List[str], Dict[str, int]]:
    """(ordered group list, {param name: group index}). Group order is
    first-appearance order of ``names`` — model declaration order — so
    "first group to go nonfinite" ties break toward earlier layers."""
    groups: List[str] = []
    index: Dict[str, int] = {}
    by_group: Dict[str, int] = {}
    for name in names:
        g = group_fn(name)
        if g not in by_group:
            by_group[g] = len(groups)
            groups.append(g)
        index[name] = by_group[g]
    return groups, index


# ---------------------------------------------------------------------------
# the in-graph stat pass (traced inside the compiled step)
# ---------------------------------------------------------------------------

def in_graph_stats(gidx: Dict[str, int], n_groups: int,
                   params: Dict[str, Any], grads: Dict[str, Any],
                   new_params: Dict[str, Any]) -> Dict[str, Any]:
    """Fused per-group reductions, traced into the caller's jit.

    Returns ``{"grad_norm","param_norm","update_norm": [G] f32,
    "nonfinite": [G] i32}``. Each entry is a sum-of-squares (or count)
    over the group's params, reduced in f32 — the same cost class as the
    step's existing global-norm clip. Global values derive host-side
    (sqrt of the summed squares), so nothing extra crosses the wire.
    """
    import jax.numpy as jnp

    gsq = [jnp.zeros((), jnp.float32) for _ in range(n_groups)]
    psq = [jnp.zeros((), jnp.float32) for _ in range(n_groups)]
    usq = [jnp.zeros((), jnp.float32) for _ in range(n_groups)]
    nonf = [jnp.zeros((), jnp.int32) for _ in range(n_groups)]
    for name, g in grads.items():
        i = gidx[name]
        g32 = g.astype(jnp.float32)
        gsq[i] = gsq[i] + jnp.sum(jnp.square(g32))
        nonf[i] = nonf[i] + jnp.sum((~jnp.isfinite(g32)).astype(jnp.int32))
        p32 = params[name].astype(jnp.float32)
        psq[i] = psq[i] + jnp.sum(jnp.square(p32))
        u32 = new_params[name].astype(jnp.float32) - p32
        usq[i] = usq[i] + jnp.sum(jnp.square(u32))
    return {
        "grad_norm": jnp.sqrt(jnp.stack(gsq)),
        "param_norm": jnp.sqrt(jnp.stack(psq)),
        "update_norm": jnp.sqrt(jnp.stack(usq)),
        "nonfinite": jnp.stack(nonf),
    }


# ---------------------------------------------------------------------------
# host-side detectors
# ---------------------------------------------------------------------------

class HealthConfig:
    """Detector knobs (all host-side — never traced, safe to tune per run).

    - ``ewma_alpha``: smoothing of the running mean/variance.
    - ``z_threshold``: |z| above which a spike fires.
    - ``warmup_steps``: observations before a detector may fire.
    - ``noise_floor``: relative std floor — a signal must move by at least
      ``z_threshold * noise_floor * |mean|`` to fire, so near-constant
      signals don't alarm on numeric dust.
    - ``capture``: write flight-recorder ``anomaly`` events.
    - ``max_anomalies``: ring bound on the kept anomaly records.
    """

    __slots__ = ("ewma_alpha", "z_threshold", "warmup_steps", "noise_floor",
                 "capture", "max_anomalies")

    def __init__(self, ewma_alpha: float = 0.05, z_threshold: float = 6.0,
                 warmup_steps: int = 10, noise_floor: float = 0.01,
                 capture: bool = True, max_anomalies: int = 256):
        self.ewma_alpha = float(ewma_alpha)
        self.z_threshold = float(z_threshold)
        self.warmup_steps = int(warmup_steps)
        self.noise_floor = float(noise_floor)
        self.capture = bool(capture)
        self.max_anomalies = int(max_anomalies)


class EwmaDetector:
    """EWMA mean/variance spike detector: z = (x - mean) / max(std, floor).

    One-sided: only UPWARD excursions fire (for loss and grad norm a fast
    drop is healthy — early training would otherwise alarm constantly).
    The z-score is computed against the state BEFORE absorbing x, and a
    firing-grade sample is excluded from the state update (a spike must
    not vouch for itself); downward moves always absorb so the tracker
    follows a fast-improving signal. Non-finite samples neither score nor
    poison the state — the nonfinite path owns those.
    """

    __slots__ = ("alpha", "z_threshold", "warmup", "noise_floor",
                 "mean", "var", "n")

    def __init__(self, alpha: float = 0.05, z_threshold: float = 6.0,
                 warmup: int = 10, noise_floor: float = 0.01):
        self.alpha, self.z_threshold = float(alpha), float(z_threshold)
        self.warmup, self.noise_floor = int(warmup), float(noise_floor)
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def observe(self, x: float) -> Optional[float]:
        """Feed one sample; returns its z-score (None for non-finite x).
        ``fired(z)`` decides whether it counts as a spike."""
        x = float(x)
        if not math.isfinite(x):
            return None
        if self.n == 0:
            self.mean, self.var, self.n = x, 0.0, 1
            return 0.0
        diff = x - self.mean
        floor = self.noise_floor * abs(self.mean)
        std = max(math.sqrt(self.var), floor, 1e-12)
        z = diff / std
        if self.n < self.warmup or z < self.z_threshold:
            self.mean += self.alpha * diff
            self.var = (1.0 - self.alpha) * (
                self.var + self.alpha * diff * diff)
        self.n += 1
        return z

    def fired(self, z: Optional[float]) -> bool:
        return (z is not None and self.n > self.warmup
                and z >= self.z_threshold)


class NonfiniteProvenance:
    """Sticky record of WHICH param group went NaN/Inf first.

    ``update(step, counts)`` returns the groups that newly turned
    non-finite this step (ordered by model declaration order). The first
    such event is pinned as ``.first`` — the forensic answer to "where did
    the NaN start" even after it propagates everywhere next step.
    """

    __slots__ = ("first", "bad", "_prev")

    def __init__(self):
        self.first: Optional[Dict[str, Any]] = None
        self.bad: set = set()
        self._prev: set = set()

    def update(self, step: int, groups: Sequence[str],
               counts: Sequence[int]) -> List[str]:
        now = [g for g, c in zip(groups, counts) if c]
        new = [g for g in now if g not in self._prev]
        self._prev = set(now)
        self.bad |= set(now)
        if new and self.first is None:
            self.first = {"step": int(step), "group": new[0],
                          "groups": list(new)}
        return new


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------

class HealthMonitor:
    """Consumes per-step (loss, in-graph stats) and raises anomalies.

    - ``on_anomaly(record)`` — caller hook (print, alert, abort...).
    - ``checkpoint_hook(record)`` — fired ONCE, on the first anomaly: the
      checkpoint-before-divergence escape hatch (state is still the
      pre-anomaly params when detection is pipelined one step behind).
    - ``data_position`` — zero-arg provider (e.g. ``pipe.get_state``)
      sampled at dispatch time so each anomaly names the offending batch.

    All emission is via ``health.*`` metrics plus flight-recorder
    ``anomaly`` events carrying the full per-group stat table.
    """

    def __init__(self, config: Optional[HealthConfig] = None,
                 groups: Optional[Sequence[str]] = None,
                 on_anomaly: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 checkpoint_hook: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 data_position: Optional[Callable[[], Any]] = None):
        self.cfg = config if config is not None else HealthConfig()
        self.groups: Optional[List[str]] = list(groups) if groups else None
        self.on_anomaly = on_anomaly
        self.checkpoint_hook = checkpoint_hook
        self._data_position_fn = data_position
        c = self.cfg
        det = lambda: EwmaDetector(c.ewma_alpha, c.z_threshold,
                                   c.warmup_steps, c.noise_floor)
        self.loss_detector = det()
        self.grad_detector = det()
        self.provenance = NonfiniteProvenance()
        self.anomalies: List[Dict[str, Any]] = []
        self.last_stats: Optional[Dict[str, Dict[str, float]]] = None
        self.steps_observed = 0
        self._prev_scale: Optional[float] = None
        self._checkpointed = False
        self._kind_counts: Dict[str, int] = {}

    # -- wiring ------------------------------------------------------------
    def bind_groups(self, groups: Sequence[str]):
        """Adopt the step's group list (ShardedTrainStep calls this from
        attach_health_monitor). Re-binding the SAME list is a no-op so the
        elastic runner can re-attach across mesh re-forms; a different
        model is a caller bug."""
        groups = list(groups)
        if self.groups is None:
            self.groups = groups
        elif self.groups != groups:
            raise ValueError(
                f"HealthMonitor bound to {len(self.groups)} group(s); "
                f"re-bind with {len(groups)} differing group(s) — one "
                "monitor per model")

    def data_position(self):
        if self._data_position_fn is None:
            return None
        try:
            return self._data_position_fn()
        except Exception:
            return None

    # -- the observation path ---------------------------------------------
    def observe(self, step: int, loss, stats=None, loss_scale=None,
                data_position=None) -> List[Dict[str, Any]]:
        """Feed one training step's outputs. ``stats`` is the in-graph
        pytree (device or host arrays); returns the anomaly records this
        step raised (possibly empty)."""
        step = int(step)
        loss_f = float(loss)
        table = self._stat_table(stats)
        scale_f = None if loss_scale is None else float(loss_scale)
        if data_position is None:
            data_position = self.data_position()

        anomalies: List[Dict[str, Any]] = []

        # nonfinite provenance (needs per-group counts from the stat pass)
        new_bad: List[str] = []
        if table is not None and self.groups:
            counts = [table[g]["nonfinite"] for g in self.groups]
            new_bad = self.provenance.update(step, self.groups, counts)
            for g in new_bad:
                anomalies.append({"anomaly": "nonfinite", "group": g,
                                  "groups": new_bad,
                                  "nonfinite": table[g]["nonfinite"]})
        elif not math.isfinite(loss_f):
            # no stat pass wired: the loss itself is the only witness
            if self.provenance.first is None:
                self.provenance.first = {"step": step, "group": None,
                                         "groups": []}
                anomalies.append({"anomaly": "loss_nonfinite", "group": None})

        # loss-scale events (dynamic fp16 scaling)
        if scale_f is not None:
            m = _metrics()
            if self._prev_scale is not None and scale_f != self._prev_scale:
                event = "backoff" if scale_f < self._prev_scale else "growth"
                if m is not None:
                    m.counter("health.loss_scale.events", 1, event=event)
                if event == "backoff":
                    # the scaler skipped the update: attribute the overflow
                    # to the group(s) the provenance resolver caught
                    blame = (new_bad[0] if new_bad else
                             (self.provenance.first or {}).get("group"))
                    anomalies.append({"anomaly": "overflow_skip",
                                      "group": blame,
                                      "scale": scale_f,
                                      "prev_scale": self._prev_scale})
            self._prev_scale = scale_f

        # spike detectors (EWMA z-score; non-finite samples skip — the
        # provenance path above already owns them)
        z_loss = self.loss_detector.observe(loss_f)
        if self.loss_detector.fired(z_loss):
            anomalies.append({"anomaly": "loss_spike", "group": None,
                              "z": round(z_loss, 3)})
        gnorm = self._global_grad_norm(table)
        z_grad = self.grad_detector.observe(gnorm) if gnorm is not None else None
        if self.grad_detector.fired(z_grad):
            blame = self._max_grad_group(table)
            anomalies.append({"anomaly": "grad_norm_spike", "group": blame,
                              "z": round(z_grad, 3)})

        self._emit_gauges(loss_f, scale_f, gnorm, table, z_loss, z_grad)
        records = [self._raise(a, step, loss_f, scale_f, table,
                               data_position) for a in anomalies]
        self.last_stats = table
        self.steps_observed += 1
        return records

    def summary(self) -> Dict[str, Any]:
        return {
            "steps_observed": self.steps_observed,
            "anomalies": len(self.anomalies),
            "kinds": dict(self._kind_counts),
            "first_nonfinite": self.provenance.first,
            "loss_scale": self._prev_scale,
        }

    # -- internals ---------------------------------------------------------
    def _stat_table(self, stats) -> Optional[Dict[str, Dict[str, float]]]:
        """Device pytree -> {group: {stat: float}} (adds update_ratio)."""
        if stats is None or not self.groups:
            return None

        def tolist(v):
            try:
                import numpy as np
                return np.asarray(v).tolist()  # one host transfer
            except Exception:
                return list(v)
        host = {k: tolist(v) for k, v in dict(stats).items()}
        host["nonfinite"] = [int(x) for x in host["nonfinite"]]
        table = {}
        for i, g in enumerate(self.groups):
            pn = host["param_norm"][i]
            un = host["update_norm"][i]
            table[g] = {
                "grad_norm": host["grad_norm"][i],
                "param_norm": pn,
                "update_norm": un,
                "update_ratio": (un / pn) if pn > 0 else 0.0,
                "nonfinite": host["nonfinite"][i],
            }
        return table

    def _global_grad_norm(self, table) -> Optional[float]:
        if table is None:
            return None
        return math.fsum(r["grad_norm"] ** 2 for r in table.values()) ** 0.5

    def _max_grad_group(self, table) -> Optional[str]:
        if not table:
            return None
        finite = {g: r["grad_norm"] for g, r in table.items()
                  if math.isfinite(r["grad_norm"])}
        src = finite or {g: r["nonfinite"] for g, r in table.items()}
        return max(src, key=src.get)

    def _emit_gauges(self, loss_f, scale_f, gnorm, table, z_loss, z_grad):
        m = _metrics()
        if m is None or not m.enabled():
            return
        m.gauge("health.loss", loss_f)
        if scale_f is not None:
            m.gauge("health.loss_scale", scale_f)
        if z_loss is not None:
            m.histogram("health.detector.z", abs(z_loss), signal="loss")
        if z_grad is not None:
            m.histogram("health.detector.z", abs(z_grad), signal="grad_norm")
        if gnorm is not None:
            m.gauge("health.grad_norm", gnorm, group=GLOBAL_GROUP)
        if table:
            for g, row in table.items():
                m.gauge("health.grad_norm", row["grad_norm"], group=g)
                m.gauge("health.param_norm", row["param_norm"], group=g)
                m.gauge("health.update_ratio", row["update_ratio"], group=g)

    def _raise(self, anomaly: Dict[str, Any], step: int, loss_f: float,
               scale_f, table, data_position) -> Dict[str, Any]:
        record = {
            "kind": "anomaly",
            "schema": SCHEMA,
            "step": step,
            "loss": loss_f,
            "loss_scale": scale_f,
            "data_position": data_position,
            "stats": table,
            **anomaly,
        }
        kind = record["anomaly"]
        self._kind_counts[kind] = self._kind_counts.get(kind, 0) + 1
        self.anomalies.append(record)
        if len(self.anomalies) > self.cfg.max_anomalies:
            del self.anomalies[0]
        m = _metrics()
        if m is not None:
            m.counter("health.anomaly", 1, kind=kind,
                      group=record.get("group") or GLOBAL_GROUP)
        if self.cfg.capture:
            fl = _flight()
            if fl is not None:
                try:
                    fl.record_event(record)
                except Exception:
                    pass
        if self.checkpoint_hook is not None and not self._checkpointed:
            self._checkpointed = True
            try:
                self.checkpoint_hook(record)
            except Exception:
                pass
        if self.on_anomaly is not None:
            try:
                self.on_anomaly(record)
            except Exception:
                pass
        return record
