"""Process-global metrics registry: counters, gauges, histograms with labels.

The measurement substrate every perf/robustness PR reports through (GSPMD /
EQuARX attribute their wins via per-collective byte accounting and compiler
pass statistics; this is the same idea as a framework service). Everything is
off by default behind ``FLAGS_observability`` (core/flags.py): a disabled
call site reduces to one flag check and the registry stays empty, so tier-1
timing is unaffected.

Metric naming scheme (see observability/README.md):

    <layer>.<subject>.<measure>{label=value,...}

e.g. ``ir.pass.seconds{pass=cse}``, ``dist.collective.bytes{op=ppermute}``,
``jit.compile.cache_miss{site=sharded_train_step}``, ``train.mfu``.

Thread safety: all mutation and the snapshot/reset API take one lock;
snapshots are deep copies so a caller can never observe a half-updated
histogram.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..core.flags import flag_value, register_flag, set_flags

register_flag(
    "observability", False,
    "Enable the runtime telemetry substrate (metrics registry + span "
    "tracer). Off by default: instrumented sites reduce to one flag check "
    "and the registry stays empty")


def enabled() -> bool:
    """One-flag gate every instrumented call site checks first."""
    return bool(flag_value("observability"))


def enable() -> None:
    set_flags({"observability": True})


def disable() -> None:
    set_flags({"observability": False})


# label sets are stored canonicalized: a sorted tuple of (key, str(value))
_LabelKey = Tuple[Tuple[str, str], ...]
_MetricKey = Tuple[str, _LabelKey]

# latency-oriented decade buckets (seconds): le-style upper bounds.
# aggregate.py (which must stay stdlib-only) mirrors this constant; a test
# asserts the two stay equal.
_BUCKET_BOUNDS = tuple(10.0 ** e for e in range(-7, 4))
BUCKET_BOUNDS = _BUCKET_BOUNDS


def _labels_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _render_key(name: str, labels: _LabelKey) -> str:
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


class _Hist:
    __slots__ = ("count", "total", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.buckets = [0] * (len(_BUCKET_BOUNDS) + 1)

    def observe(self, value: float):
        self.count += 1
        self.total += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        self.buckets[bisect.bisect_left(_BUCKET_BOUNDS, value)] += 1

    def percentile(self, q: float) -> float:
        """Estimate the q-quantile (0..1) from the decade buckets: find the
        bucket the rank falls in, interpolate linearly inside it, clamp to
        the observed [min, max] so single-bucket histograms stay exact-ish."""
        if not self.count:
            return 0.0
        target = q * self.count
        cum = 0
        for i, n in enumerate(self.buckets):
            if not n:
                continue
            if cum + n >= target:
                lo = _BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
                hi = (_BUCKET_BOUNDS[i] if i < len(_BUCKET_BOUNDS)
                      else self.max)
                lo = max(lo, self.min)
                hi = min(hi, self.max)
                if hi < lo:
                    hi = lo
                return lo + (hi - lo) * ((target - cum) / n)
            cum += n
        return self.max

    def as_dict(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "sum": self.total,
            "avg": self.total / self.count if self.count else 0.0,
            "min": self.min if self.count else 0.0,
            "max": self.max if self.count else 0.0,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
            "buckets": list(self.buckets),
        }


class MetricsRegistry:
    """Counters / gauges / histograms keyed by (name, labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[_MetricKey, float] = {}
        self._gauges: Dict[_MetricKey, float] = {}
        self._hists: Dict[_MetricKey, _Hist] = {}

    # -- mutation (callers gate on enabled(); these never gate themselves so
    #    tests can drive the registry directly) --
    def counter(self, name: str, value: float = 1, **labels):
        key = (name, _labels_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    def gauge(self, name: str, value: float, **labels):
        with self._lock:
            self._gauges[(name, _labels_key(labels))] = value

    def histogram(self, name: str, value: float, **labels):
        key = (name, _labels_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Hist()
            h.observe(value)

    # -- read side --
    def snapshot(self, reset: bool = False) -> Dict[str, Dict[str, Any]]:
        """{'counters': {key: v}, 'gauges': {...}, 'histograms': {...}} with
        rendered ``name{label=value}`` keys; a deep copy, isolated from
        later mutation. ``reset=True`` atomically clears after copying."""
        with self._lock:
            out = {
                "counters": {_render_key(*k): v
                             for k, v in self._counters.items()},
                "gauges": {_render_key(*k): v
                           for k, v in self._gauges.items()},
                "histograms": {_render_key(*k): h.as_dict()
                               for k, h in self._hists.items()},
            }
            if reset:
                self._counters.clear()
                self._gauges.clear()
                self._hists.clear()
        return out

    def records(self) -> List[Dict[str, Any]]:
        """Structured (labels kept as a dict) records, for JSON-lines."""
        with self._lock:
            recs: List[Dict[str, Any]] = []
            for (name, labels), v in self._counters.items():
                recs.append({"type": "counter", "name": name,
                             "labels": dict(labels), "value": v})
            for (name, labels), v in self._gauges.items():
                recs.append({"type": "gauge", "name": name,
                             "labels": dict(labels), "value": v})
            for (name, labels), h in self._hists.items():
                recs.append({"type": "histogram", "name": name,
                             "labels": dict(labels), **h.as_dict()})
        return sorted(recs, key=lambda r: (r["type"], r["name"],
                                           sorted(r["labels"].items())))

    def hist_totals(self, name: str) -> Tuple[float, int]:
        """(sum, count) across every label set of one histogram name — the
        cheap delta source goodput.py polls every step."""
        total, count = 0.0, 0
        with self._lock:
            for (n, _), h in self._hists.items():
                if n == name:
                    total += h.total
                    count += h.count
        return total, count

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    def __len__(self):
        with self._lock:
            return len(self._counters) + len(self._gauges) + len(self._hists)


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _registry


# -- module-level API: the flag-gated face instrumentation sites call --
def counter(name: str, value: float = 1, **labels):
    if enabled():
        _registry.counter(name, value, **labels)


def gauge(name: str, value: float, **labels):
    if enabled():
        _registry.gauge(name, value, **labels)


def histogram(name: str, value: float, **labels):
    if enabled():
        _registry.histogram(name, value, **labels)


def snapshot(reset: bool = False) -> Dict[str, Dict[str, Any]]:
    return _registry.snapshot(reset=reset)


def hist_totals(name: str) -> Tuple[float, int]:
    return _registry.hist_totals(name)


def reset():
    _registry.reset()


def dump_jsonl(path: str, reset: bool = False) -> str:
    """Write one JSON object per metric (tools/metrics_dump.py reads this)."""
    ts = time.time()
    with open(path, "w") as f:
        for rec in _registry.records():
            f.write(json.dumps({**rec, "ts": ts}) + "\n")
    if reset:
        _registry.reset()
    return path


def summary() -> str:
    """Text table of the live registry (profiler.summary() analog)."""
    snap = _registry.snapshot()
    lines = []
    if snap["counters"]:
        lines.append(f"{'Counter':<56}{'Value':>16}")
        lines.append("-" * 72)
        for k in sorted(snap["counters"]):
            v = snap["counters"][k]
            sv = f"{v:.6g}" if isinstance(v, float) and v != int(v) else f"{int(v)}"
            lines.append(f"{k[:55]:<56}{sv:>16}")
    if snap["gauges"]:
        if lines:
            lines.append("")
        lines.append(f"{'Gauge':<56}{'Value':>16}")
        lines.append("-" * 72)
        for k in sorted(snap["gauges"]):
            lines.append(f"{k[:55]:<56}{snap['gauges'][k]:>16.6g}")
    if snap["histograms"]:
        if lines:
            lines.append("")
        lines.append(f"{'Histogram':<46}{'Count':>8}{'Sum':>12}"
                     f"{'Avg':>12}{'Min':>12}{'Max':>12}")
        lines.append("-" * 102)
        for k in sorted(snap["histograms"]):
            h = snap["histograms"][k]
            lines.append(
                f"{k[:45]:<46}{h['count']:>8}{h['sum']:>12.6g}"
                f"{h['avg']:>12.6g}{h['min']:>12.6g}{h['max']:>12.6g}")
    return "\n".join(lines) if lines else "(registry empty)"
