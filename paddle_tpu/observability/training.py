"""Per-step training telemetry: tokens/sec, achieved FLOPs, MFU.

The shared arithmetic bench.py and the fleet training loops report through
instead of private computation — so every BENCH_*.json round and any training
loop derive MFU the same way from the same registry.
"""

from __future__ import annotations

from typing import Optional

from . import metrics
from .attribution import hardware_for_backend


def peak_flops(backend: Optional[str] = None) -> float:
    """Per-chip peak FLOP/s the MFU denominator uses — read from
    ``attribution.HW_SPECS`` (the roofline table), so MFU and the
    roofline floors can never quote different peaks for one backend
    (a pin test in tests/test_attribution.py holds them equal)."""
    if backend is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
    return hardware_for_backend(backend).peak_flops


def record_step(*, seconds: Optional[float] = None,
                samples: Optional[int] = None,
                tokens: Optional[int] = None, **labels):
    """One training step dispatched (fleet ShardedTrainStep calls this)."""
    if not metrics.enabled():
        return
    metrics.counter("train.steps", 1, **labels)
    if seconds is not None:
        metrics.histogram("train.step.seconds", seconds, **labels)
    if samples:
        metrics.counter("train.samples", samples, **labels)
    if tokens:
        metrics.counter("train.tokens", tokens, **labels)


def record_window(*, tokens: Optional[int] = None,
                  seconds: Optional[float] = None,
                  flops: Optional[float] = None,
                  peak: Optional[float] = None,
                  tokens_per_sec: Optional[float] = None,
                  mfu: Optional[float] = None, **labels):
    """Aggregate telemetry for a timed window of steps: derives (or accepts
    pre-computed) throughput and MFU gauges.

    bench.py field mapping: ``value``/``tokens_per_sec`` ->
    ``train.tokens_per_sec``, ``mfu`` -> ``train.mfu``, achieved FLOP/s ->
    ``train.achieved_flops``."""
    if not metrics.enabled():
        return
    if tokens_per_sec is None and tokens and seconds:
        tokens_per_sec = tokens / seconds
    if tokens_per_sec is not None:
        metrics.gauge("train.tokens_per_sec", tokens_per_sec, **labels)
    achieved = flops / seconds if (flops and seconds) else None
    if achieved is not None:
        metrics.gauge("train.achieved_flops", achieved, **labels)
    if mfu is None and achieved is not None:
        mfu = achieved / (peak if peak else peak_flops())
    if mfu is not None:
        metrics.gauge("train.mfu", mfu, **labels)
