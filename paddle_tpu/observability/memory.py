"""HBM / host memory accounting from compiled executables and live buffers.

``record_executable(site, compiled)`` turns an AOT ``Compiled``'s
``memory_analysis()`` (XLA's ``CompiledMemoryStats``) into per-site gauges —
the compile-time answer to "will this step fit in HBM", available before the
first real dispatch. ``record_live_buffers()`` sums every live ``jax.Array``
on this host for the runtime answer. Both gate on ``metrics.enabled()`` and
swallow backend gaps (CPU has no ``memory_stats``; pathways-style backends
may omit ``memory_analysis``), so call sites stay one line.

Gauges (all labelled ``site=`` where applicable):

    mem.exe.temp_bytes / argument_bytes / output_bytes / code_bytes /
    alias_bytes   — raw CompiledMemoryStats fields per executable
    mem.exe.peak_bytes — arg + out + temp + code - alias (HBM high-water
                         estimate for one dispatch of this executable)
    mem.live.bytes / mem.live.count — live jax.Array payload on this host
    mem.device.bytes_in_use{device=} — allocator stats where the backend
                         exposes them (TPU yes, CPU no)
    mem.kv_cache.bytes — serving KV-cache footprint
"""

from __future__ import annotations

from typing import Any

from . import metrics

# (gauge suffix, CompiledMemoryStats attribute)
_EXE_FIELDS = (
    ("temp", "temp_size_in_bytes"),
    ("argument", "argument_size_in_bytes"),
    ("output", "output_size_in_bytes"),
    ("code", "generated_code_size_in_bytes"),
    ("alias", "alias_size_in_bytes"),
)


def record_executable(site: str, compiled: Any, **labels) -> bool:
    """Gauge the ``memory_analysis()`` of one AOT-compiled executable.

    Returns True when stats were recorded (False: flag off or the backend
    does not expose memory analysis)."""
    if not metrics.enabled():
        return False
    try:
        stats = compiled.memory_analysis()
    except Exception:
        return False
    if stats is None:
        return False
    peak = 0.0
    seen = False
    for kind, attr in _EXE_FIELDS:
        v = getattr(stats, attr, None)
        if v is None:
            continue
        seen = True
        metrics.gauge(f"mem.exe.{kind}_bytes", float(v), site=site, **labels)
        peak += -float(v) if kind == "alias" else float(v)
    if seen:
        metrics.gauge("mem.exe.peak_bytes", max(peak, 0.0),
                      site=site, **labels)
    return seen


def record_live_buffers() -> None:
    """Gauge the count and summed bytes of every live jax.Array this host
    can see (committed + uncommitted). O(live arrays) — call at step
    granularity, not inside inner loops."""
    if not metrics.enabled():
        return
    try:
        import jax

        count, nbytes = 0, 0
        for a in jax.live_arrays():
            count += 1
            nbytes += int(getattr(a, "nbytes", 0) or 0)
    except Exception:
        return
    metrics.gauge("mem.live.count", count)
    metrics.gauge("mem.live.bytes", nbytes)


def record_device_memory() -> None:
    """Gauge allocator stats per local device where the backend exposes
    them (``Device.memory_stats()`` — TPU/GPU; None on CPU)."""
    if not metrics.enabled():
        return
    try:
        import jax

        for d in jax.local_devices():
            ms = d.memory_stats()
            if not ms:
                continue
            for key, gname in (("bytes_in_use", "mem.device.bytes_in_use"),
                               ("peak_bytes_in_use",
                                "mem.device.peak_bytes_in_use"),
                               ("bytes_limit", "mem.device.bytes_limit")):
                if key in ms:
                    metrics.gauge(gname, float(ms[key]), device=str(d.id))
    except Exception:
        return


def record_kv_cache(nbytes: int, **labels) -> None:
    """Serving KV-cache footprint (the dominant serving HBM consumer)."""
    if not metrics.enabled():
        return
    metrics.gauge("mem.kv_cache.bytes", float(nbytes), **labels)
