"""Background checkpoint writer with loud failures.

The save path splits in two: the device->host snapshot happens synchronously
on the caller's thread (the only step-blocking cost — see
``CheckpointManager.save``), and the disk I/O runs here, on one ordered
worker thread per writer. Ordering matters: step N's COMMIT must not race
step N+1's shard writes, and a single FIFO worker gives that for free.

Failure contract (the fix for framework/io.py's silently-dying save thread):
an exception in a background write is recorded and re-raised on the NEXT
``submit``/``wait_until_finished`` call — a failed checkpoint save must
surface in the training loop, not vanish with a daemon thread. Once raised
the error is cleared; pending work submitted after the failing item still
runs (each item is independent — a later save to a healthy path should not
be poisoned by an earlier full disk).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

from ..observability import metrics as _metrics


class AsyncCheckpointError(RuntimeError):
    """A background checkpoint write failed (original exception chained)."""


class AsyncWriter:
    def __init__(self, name: str = "ckpt-writer"):
        self._name = name
        self._queue: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        self._lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name=self._name, daemon=True)
            self._thread.start()

    def _run(self):
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                try:
                    item()
                except BaseException as e:  # noqa: BLE001 — recorded, re-raised on next call
                    _metrics.counter("ckpt.async.failures")
                    with self._lock:
                        if self._error is None:
                            self._error = e
            finally:
                self._queue.task_done()

    def _raise_pending(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise AsyncCheckpointError(
                f"a background checkpoint write failed: {err!r}") from err

    def submit(self, fn: Callable[[], None]):
        """Queue `fn`; raises first if a previous background write failed."""
        if self._closed:
            raise RuntimeError(f"AsyncWriter {self._name!r} is closed")
        self._raise_pending()
        self._ensure_thread()
        self._queue.put(fn)

    def run_sync(self, fn: Callable[[], None]):
        """Synchronous mode (async_=False): same failure surfacing, caller's
        thread, still ordered AFTER any queued async work."""
        if self._closed:
            raise RuntimeError(f"AsyncWriter {self._name!r} is closed")
        self.wait_until_finished()
        fn()

    def wait_until_finished(self):
        """Block until every queued write has run; re-raise any failure."""
        self._queue.join()
        self._raise_pending()

    def close(self):
        self._closed = True
        self.wait_until_finished()
        if self._thread is not None and self._thread.is_alive():
            self._queue.put(None)
            self._thread.join(timeout=10)
        self._thread = None
