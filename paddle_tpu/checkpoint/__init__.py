"""paddle_tpu.checkpoint — fault-tolerant distributed checkpointing.

Async sharded save with atomic commit, integrity-checked restore, and
restore-time resharding onto a changed mesh. See checkpoint/README.md for
the commit protocol and manifest format.

    from paddle_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager("/ckpts/run1", keep_last_n=3)   # async by default
    mgr.save(step, train_step.state_for_checkpoint().to_tree())
    ...
    tree = mgr.restore()                      # latest committed step
    train_step.restore_from_checkpoint(tree)  # bitwise-faithful resume
"""

from . import arrays, async_writer, manager, train_state  # noqa: F401
from .arrays import load_tree, restore_array, save_tree  # noqa: F401
from .async_writer import AsyncCheckpointError, AsyncWriter  # noqa: F401
from .manager import CheckpointManager  # noqa: F401
from .train_state import TrainState, is_train_state_tree  # noqa: F401

__all__ = [
    "CheckpointManager", "TrainState", "is_train_state_tree",
    "AsyncWriter", "AsyncCheckpointError",
    "save_tree", "load_tree", "restore_array",
]
