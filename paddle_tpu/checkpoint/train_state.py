"""TrainState: everything a bitwise-faithful resume needs, as ONE tree.

The reference scatters resume state across files (persistables, optimizer
.pdopt, the RNG tracker, the reader's position); a preemption that catches
them out of sync resumes a subtly different run. Here the composite —
params, optimizer state, buffers (BN stats), RNG position, step counter,
data-iterator position, and any extra leaves (loss-scaler automaton) — is
checkpointed atomically as one tree under one COMMIT, so the restored run
continues the exact token/dropout/update sequence the interrupted one would
have produced.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

_TREE_TAG = "paddle_tpu.train_state.v1"


@dataclasses.dataclass
class TrainState:
    """params/opt_state are {name: array} / {name: {slot: array}} trees (the
    ShardedTrainStep layout); rng is the generator state ({"seed", "offset"}
    or the step's base seed); data_position is whatever the input pipeline
    needs to reposition itself (int sample count, dict, ...)."""

    params: Dict[str, Any]
    opt_state: Dict[str, Any]
    buffers: Optional[Dict[str, Any]] = None
    rng: Optional[Dict[str, int]] = None
    step: int = 0
    data_position: Any = None
    extra: Optional[Dict[str, Any]] = None

    def to_tree(self) -> Dict[str, Any]:
        """The checkpointable nested-dict form (None fields omitted)."""
        tree: Dict[str, Any] = {
            "__train_state__": _TREE_TAG,
            "step": int(self.step),
            "params": self.params,
            "opt_state": self.opt_state,
        }
        for name in ("buffers", "rng", "data_position", "extra"):
            v = getattr(self, name)
            if v is not None:
                tree[name] = v
        return tree

    @classmethod
    def from_tree(cls, tree: Dict[str, Any]) -> "TrainState":
        if tree.get("__train_state__") != _TREE_TAG:
            raise ValueError(
                "checkpoint tree is not a TrainState (missing/foreign "
                f"'__train_state__' tag: {tree.get('__train_state__')!r})")
        return cls(
            params=tree["params"],
            opt_state=tree["opt_state"],
            buffers=tree.get("buffers"),
            rng=tree.get("rng"),
            step=int(tree["step"]),
            data_position=tree.get("data_position"),
            extra=tree.get("extra"),
        )

    def shardings_like(self, param_shardings=None, state_shardings=None
                       ) -> Dict[str, Any]:
        """A shardings tree aligned with to_tree(): params/opt_state get the
        supplied layouts, everything else restores as host values."""
        out: Dict[str, Any] = {}
        if param_shardings is not None:
            out["params"] = param_shardings
        if state_shardings is not None:
            out["opt_state"] = state_shardings
        return out


def is_train_state_tree(tree) -> bool:
    return isinstance(tree, dict) and tree.get("__train_state__") == _TREE_TAG
