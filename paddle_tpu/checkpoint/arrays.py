"""Per-host sharded array serialization (the GSPMD-native checkpoint layout).

Each process writes ONLY its addressable replica-0 shards — cooperative
multi-host saves need no cross-host data movement, just a shared filesystem
(the tensorstore/OCDBT assumption, without the dependency). A JSON manifest
records, per array: global shape, dtype, the NamedSharding it was saved
under (mesh axes/shape + PartitionSpec, informational), and per-shard-file
offsets + CRC32 checksums. Restore validates checksums and reassembles under
a caller-supplied — possibly different — mesh via
``jax.make_array_from_callback``: each device's slice is built by reading
only the saved shard files that overlap it (the memory-efficient
redistribution idea of arXiv 2112.01075, done at deserialization time), so a
save under mesh (2,2) restores onto mesh (4,), (8,), or a single host numpy
array without ever holding more than the requested slices plus the touched
shard files.

State trees are nested dicts/lists/tuples whose leaves are arrays
(jax.Array / numpy / paddle Tensor) or JSON scalars (int/float/str/bool/
None). Tuples round-trip as lists (same treedef for every consumer here).
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Any, Dict, Optional

import numpy as np

MANIFEST_NAME = "manifest.json"
FORMAT = "paddle_tpu.ckpt.v1"

_SEP = "/"
_ARRAY_KEY = "__array__"


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, falling back to ml_dtypes (bfloat16, fp8, ...)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _is_array_leaf(v) -> bool:
    import jax

    from ..core.tensor import Tensor

    return isinstance(v, (jax.Array, np.ndarray, np.generic, Tensor))


def _as_host_or_jax(v):
    """Unwrap Tensor; numpy scalars become 0-d arrays."""
    from ..core.tensor import Tensor

    if isinstance(v, Tensor):
        return v._value
    if isinstance(v, np.generic):
        return np.asarray(v)
    return v


def flatten_tree(state) -> Dict[str, Any]:
    """Nested containers -> {path: leaf} with '/'-joined string paths."""
    out: Dict[str, Any] = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                k = str(k)
                if _SEP in k:
                    raise ValueError(f"state key may not contain '{_SEP}': {k!r}")
                walk(f"{prefix}{_SEP}{k}" if prefix else k, v)
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(f"{prefix}{_SEP}{i}" if prefix else str(i), v)
        else:
            out[prefix] = node

    walk("", state)
    return out


def _structure(state, arrays: Dict[str, Any], prefix: str = ""):
    """Nesting skeleton for the manifest: array leaves become
    {"__array__": path} markers, scalars stay inline JSON."""
    if isinstance(state, dict):
        return {str(k): _structure(v, arrays,
                                   f"{prefix}{_SEP}{k}" if prefix else str(k))
                for k, v in state.items()}
    if isinstance(state, (list, tuple)):
        return [_structure(v, arrays, f"{prefix}{_SEP}{i}" if prefix else str(i))
                for i, v in enumerate(state)]
    if _is_array_leaf(state):
        return {_ARRAY_KEY: prefix}
    if state is None or isinstance(state, (bool, int, float, str)):
        return state
    raise TypeError(
        f"unsupported checkpoint leaf at {prefix!r}: {type(state).__name__} "
        "(arrays, numbers, strings, bools, None, and nested "
        "dict/list/tuple containers are checkpointable)")


def _unstructure(node, resolve_array):
    if isinstance(node, dict):
        if _ARRAY_KEY in node and len(node) == 1:
            return resolve_array(node[_ARRAY_KEY])
        return {k: _unstructure(v, resolve_array) for k, v in node.items()}
    if isinstance(node, list):
        return [_unstructure(v, resolve_array) for v in node]
    return node


def _file_name(path: str, offsets) -> str:
    """Deterministic shard file name: offsets make cooperative multi-host
    writes collision-free (distinct shards -> distinct names; replicas of
    the same shard are written by replica 0 only)."""
    base = path.replace(_SEP, "__")
    if not offsets:
        return f"{base}.scalar.bin"
    return f"{base}.o{'_'.join(str(o) for o in offsets)}.bin"


def _sharding_desc(arr) -> Optional[dict]:
    from jax.sharding import NamedSharding, PartitionSpec

    sh = getattr(arr, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return None

    def ent(e):
        if e is None:
            return None
        if e is PartitionSpec.UNCONSTRAINED:
            return "__unconstrained__"
        if isinstance(e, tuple):
            return list(e)
        return e

    return {
        "mesh_axes": list(sh.mesh.axis_names),
        "mesh_shape": [int(d) for d in sh.mesh.devices.shape],
        "spec": [ent(e) for e in sh.spec],
    }


def _index_offsets(index, shape):
    return [int(sl.start or 0) for sl in index] if index else []


def snapshot_array(arr) -> dict:
    """Device->host snapshot of this process's replica-0 shards — the ONLY
    step-blocking part of a save. Returns {"global_shape", "dtype",
    "sharding", "shards": [(offsets, host numpy)]}; the disk write
    (``write_snapshot``) can then run on a background thread against data
    the training step can no longer mutate (donated buffers included)."""
    import jax

    v = _as_host_or_jax(arr)
    shards = []
    if isinstance(v, jax.Array) and hasattr(v, "addressable_shards"):
        global_shape = tuple(int(d) for d in v.shape)
        dtype = str(v.dtype)
        sharding = _sharding_desc(v)
        for s in v.addressable_shards:
            if s.replica_id != 0:
                continue
            data = np.ascontiguousarray(np.asarray(s.data))
            # jax 0.4.x hands back (1,)-shaped shard data for 0-d arrays;
            # normalize to the extent the shard index implies
            want = tuple(
                (self_dim if sl.stop is None else sl.stop) - (sl.start or 0)
                for sl, self_dim in zip(s.index, global_shape))
            if data.shape != want:
                data = data.reshape(want)
            shards.append((_index_offsets(s.index, global_shape), data))
    else:
        host = np.asarray(v)
        # ascontiguousarray promotes 0-d to (1,); keep the true shape
        data = np.ascontiguousarray(host).reshape(host.shape)
        global_shape = data.shape
        dtype = str(data.dtype)
        sharding = None
        if jax.process_index() == 0:
            shards.append(([0] * data.ndim, data.copy()))
    return {"global_shape": [int(d) for d in global_shape], "dtype": dtype,
            "sharding": sharding, "shards": shards}


def write_snapshot(directory: str, path: str, snap: dict) -> dict:
    """Write one snapshotted array's shard files; return its manifest entry.

    Entry shards cover only what THIS process wrote — multi-process saves
    merge the per-process entries (same global metadata, concatenated shard
    lists) before publishing the manifest.
    """
    entries = []
    total = 0
    for offsets, data in snap["shards"]:
        fname = _file_name(path, offsets)
        raw = data.tobytes()
        with open(os.path.join(directory, fname), "wb") as f:
            f.write(raw)
        total += len(raw)
        entries.append({
            "file": fname,
            "offset": offsets,
            "shape": [int(d) for d in data.shape],
            "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
            "bytes": len(raw),
        })
    return {
        "global_shape": snap["global_shape"],
        "dtype": snap["dtype"],
        "sharding": snap["sharding"],
        "shards": entries,
        "_bytes_written": total,  # stripped before the manifest is published
    }


def save_array(directory: str, path: str, arr) -> dict:
    """Snapshot + write in one call (the synchronous compat path)."""
    return write_snapshot(directory, path, snapshot_array(arr))


def save_tree(directory: str, state, step: Optional[int] = None,
              manifest_name: str = MANIFEST_NAME) -> dict:
    """Write every leaf of `state` under `directory` and return the manifest
    dict (the caller publishes it — the manager only after all processes
    finish, via the COMMIT protocol)."""
    os.makedirs(directory, exist_ok=True)
    flat = flatten_tree(state)
    arrays = {}
    total = 0
    for path, leaf in flat.items():
        if _is_array_leaf(leaf):
            entry = save_array(directory, path, leaf)
            total += entry.pop("_bytes_written")
            arrays[path] = entry
    manifest = {
        "format": FORMAT,
        "step": step,
        "structure": _structure(state, arrays),
        "arrays": arrays,
        "bytes_written": total,
    }
    if manifest_name:
        write_manifest(directory, manifest, manifest_name)
    return manifest


def write_manifest(directory: str, manifest: dict,
                   manifest_name: str = MANIFEST_NAME):
    tmp = os.path.join(directory, manifest_name + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(directory, manifest_name))


def read_manifest(directory: str, manifest_name: str = MANIFEST_NAME) -> dict:
    with open(os.path.join(directory, manifest_name)) as f:
        m = json.load(f)
    if m.get("format") != FORMAT:
        raise ValueError(f"{directory}: not a {FORMAT} checkpoint "
                         f"(format={m.get('format')!r})")
    return m


def merge_manifests(parts) -> dict:
    """Union per-process manifests (same structure/metadata, disjoint shard
    lists) into the publishable one."""
    merged = None
    for part in parts:
        if merged is None:
            merged = json.loads(json.dumps(part))
            continue
        merged["bytes_written"] += part.get("bytes_written", 0)
        for path, entry in part["arrays"].items():
            if path in merged["arrays"]:
                have = {s["file"] for s in merged["arrays"][path]["shards"]}
                merged["arrays"][path]["shards"] += [
                    s for s in entry["shards"] if s["file"] not in have]
            else:
                merged["arrays"][path] = entry
    return merged


# transient-I/O policy for restore reads: a flaky network filesystem (the
# production checkpoint home) fails reads that succeed moments later, and a
# preempted run's replacement must not die on the first EIO of a 10k-shard
# restore. Counted as ckpt.restore.retries; exhaustion re-raises with the
# shard path. Tests monkeypatch these.
RESTORE_READ_RETRIES = 2         # extra attempts after the first failure
RESTORE_RETRY_BACKOFF_S = 0.05   # doubles per attempt


class _ShardReader:
    """Lazy, checksum-validating access to one array's saved shards.

    ``read_index`` materializes an arbitrary global slice by loading ONLY
    the overlapping shard files — the unit the resharding restore path
    works in. Loaded shards are cached so a restore that touches a shard
    from several target slices reads and validates it once.
    """

    def __init__(self, directory: str, path: str, entry: dict,
                 validate: bool = True):
        self.directory = directory
        self.path = path
        self.entry = entry
        self.validate = validate
        self.dtype = _np_dtype(entry["dtype"])
        self.global_shape = tuple(entry["global_shape"])
        self._cache: Dict[str, np.ndarray] = {}

    def _read_validated(self, fpath: str, shard: dict) -> bytes:
        with open(fpath, "rb") as f:
            raw = f.read()
        if self.validate:
            crc = zlib.crc32(raw) & 0xFFFFFFFF
            if crc != shard["crc32"]:
                raise IOError(
                    f"checksum mismatch for {self.path!r} shard "
                    f"{shard['file']}: manifest {shard['crc32']:#x}, "
                    f"file {crc:#x} — checkpoint is corrupt")
        return raw

    def _load(self, shard: dict) -> np.ndarray:
        data = self._cache.get(shard["file"])
        if data is not None:
            return data
        fpath = os.path.join(self.directory, shard["file"])
        retries = max(0, int(RESTORE_READ_RETRIES))
        for attempt in range(retries + 1):
            try:
                raw = self._read_validated(fpath, shard)
                break
            except (OSError, IOError) as e:
                # covers both the open/read syscall failing and a checksum
                # mismatch (a torn page-cache read heals the same way)
                if attempt == retries:
                    raise IOError(
                        f"restore of {self.path!r} failed after "
                        f"{retries + 1} attempt(s) on shard file {fpath}: "
                        f"{e}") from e
                from ..observability import metrics as _metrics

                _metrics.counter("ckpt.restore.retries")
                time.sleep(RESTORE_RETRY_BACKOFF_S * (2.0 ** attempt))
        data = np.frombuffer(raw, dtype=self.dtype).reshape(shard["shape"])
        self._cache[shard["file"]] = data
        return data

    def read_index(self, index) -> np.ndarray:
        """Assemble the global slice `index` (tuple of slices)."""
        starts = [sl.start or 0 for sl in index] if index else []
        stops = [self.global_shape[i] if sl.stop is None else sl.stop
                 for i, sl in enumerate(index)] if index else []
        shape = [b - a for a, b in zip(starts, stops)]
        out = out_filled = None  # allocate lazily: whole-shard hits copy nothing
        for shard in self.entry["shards"]:
            s_off = shard["offset"]
            s_shape = shard["shape"]
            lo = [max(a, o) for a, o in zip(starts, s_off)]
            hi = [min(b, o + n) for b, o, n in zip(stops, s_off, s_shape)]
            if any(l >= h for l, h in zip(lo, hi)):
                continue
            data = self._load(shard)
            if out is None and starts == s_off and shape == s_shape:
                return data  # exactly one whole shard file: zero-copy
            src = tuple(slice(l - o, h - o) for l, o, h in zip(lo, s_off, hi))
            if out is None:
                out = np.empty(shape, dtype=self.dtype)
                out_filled = np.zeros(shape, dtype=bool)
            dst = tuple(slice(l - a, h - a) for l, a, h in zip(lo, starts, hi))
            out[dst] = data[src]
            out_filled[dst] = True
        if out is None or not out_filled.all():
            raise IOError(
                f"checkpoint for {self.path!r} is missing shard data for "
                f"slice {index} (torn or foreign-topology save without a "
                "merged manifest?)")
        return out

    def read_full(self) -> np.ndarray:
        return self.read_index(tuple(slice(0, n) for n in self.global_shape))


def restore_array(directory: str, path: str, entry: dict, sharding=None,
                  validate: bool = True):
    """One array back: host numpy without a sharding, or a jax.Array laid
    out per `sharding` (a NamedSharding on ANY mesh — resharding happens
    here, shard-file-granular reads, no full-array host materialization
    unless the target layout requires it)."""
    reader = _ShardReader(directory, path, entry, validate=validate)
    if sharding is None:
        return reader.read_full()
    import jax

    return jax.make_array_from_callback(
        reader.global_shape, sharding, lambda idx: reader.read_index(idx))


def _live_reshard(leaf, entry: dict, sharding):
    """Planner-driven device-to-device restore of one leaf, or None when
    the live source doesn't match the checkpoint (shape/dtype drift) or
    isn't a mesh-resident jax array — the caller then reads files."""
    import jax
    from jax.sharding import NamedSharding

    from ..distributed import resharding as _resharding

    leaf = _as_host_or_jax(leaf)
    if not (isinstance(leaf, jax.Array)
            and isinstance(getattr(leaf, "sharding", None), NamedSharding)
            and isinstance(sharding, NamedSharding)):
        return None
    if (list(leaf.shape) != list(entry["global_shape"])
            or str(leaf.dtype) != entry["dtype"]):
        return None
    try:
        plan = _resharding.plan_for(leaf, sharding)
    except _resharding.Unplannable:
        return None
    return _resharding.reshard(leaf, sharding, plan=plan)


def load_tree(directory: str, shardings=None, validate: bool = True,
              manifest: Optional[dict] = None, live_state=None):
    """Restore the full state tree. `shardings` may be a flat
    {path: NamedSharding} dict or a nested tree mirroring the state (None
    leaves = host numpy).

    `live_state` (optional, same structure) supplies arrays that are still
    resident on a mesh — e.g. the pre-reconfiguration TrainState during an
    elastic topology change. Leaves found there move device-to-device
    through the resharding planner (bitwise-identical to the file path,
    no host round trip); anything missing, mismatched, or unplannable
    falls back to the shard-file reads below."""
    m = manifest if manifest is not None else read_manifest(directory)
    flat_sh: Dict[str, Any] = {}
    if shardings:
        for p, s in flatten_tree(shardings).items():
            if s is not None:
                flat_sh[p] = s
    flat_live: Dict[str, Any] = {}
    if live_state is not None:
        flat_live = flatten_tree(live_state)

    def resolve(path):
        entry = m["arrays"].get(path)
        if entry is None:
            raise KeyError(f"array {path!r} not present in checkpoint")
        sharding = flat_sh.get(path)
        if path in flat_live and sharding is not None:
            out = _live_reshard(flat_live[path], entry, sharding)
            if out is not None:
                return out
        return restore_array(directory, path, entry,
                             sharding=sharding, validate=validate)

    return _unstructure(m["structure"], resolve)
