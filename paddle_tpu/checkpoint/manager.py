"""CheckpointManager: step directories, atomic COMMIT, keep-last-N GC.

Directory layout (one manager directory, many steps):

    <directory>/
      step_00000100/
        manifest.json            # arrays + structure + checksums
        COMMIT                   # atomic publish marker, written LAST
        params__w.o0_0.bin       # per-host shard files
        ...
      step_00000200/ ...

Commit protocol: a step is visible to ``latest_step``/``all_steps``/
``restore`` ONLY once its COMMIT marker exists, and COMMIT is written (via
tmp + rename) strictly after every shard file and the manifest have landed.
A save killed mid-write leaves a torn, invisible directory that the next
manager construction garbage-collects. Multi-process saves barrier before
process 0 merges the per-process manifest parts and publishes COMMIT, so a
partially-written cooperative save is equally invisible.

Async saves: ``save`` blocks only for the device->host snapshot; shard
files, manifest, COMMIT, and GC run on the ordered background writer, whose
failures surface on the next ``save``/``wait_until_finished`` (see
async_writer.py). ``keep_last_n`` GC never deletes the newest committed
step.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from . import arrays as _arrays
from .async_writer import AsyncWriter

STEP_PREFIX = "step_"
COMMIT_NAME = "COMMIT"


def step_dir_name(step: int) -> str:
    if step < 0:
        raise ValueError(f"checkpoint step must be >= 0, got {step}")
    return f"{STEP_PREFIX}{step:08d}"


def parse_step(name: str) -> Optional[int]:
    if not name.startswith(STEP_PREFIX):
        return None
    try:
        return int(name[len(STEP_PREFIX):])
    except ValueError:
        return None


def is_committed(step_path: str) -> bool:
    return os.path.exists(os.path.join(step_path, COMMIT_NAME))


def _sync_processes(tag: str):
    """Cross-host barrier for cooperative saves (no-op single-process)."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


class CheckpointManager:
    """save/restore/latest_step/all_steps/wait_until_finished over one
    checkpoint directory. See module docstring for the commit protocol."""

    def __init__(self, directory: str, keep_last_n: Optional[int] = None,
                 async_: bool = True, validate_on_restore: bool = True):
        import jax

        self.directory = os.path.abspath(str(directory))
        self.keep_last_n = keep_last_n
        self.async_ = async_
        self.validate_on_restore = validate_on_restore
        self._proc = jax.process_index()
        self._writer = AsyncWriter(name=f"ckpt-writer:{self.directory}")
        os.makedirs(self.directory, exist_ok=True)
        self._gc_uncommitted()

    # ---------------- step discovery ----------------
    def all_steps(self) -> List[int]:
        """Committed steps, ascending. Torn/in-flight saves are invisible."""
        out = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        for name in names:
            step = parse_step(name)
            if step is None:
                continue
            if is_committed(os.path.join(self.directory, name)):
                out.append(step)
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def step_path(self, step: int) -> str:
        return os.path.join(self.directory, step_dir_name(step))

    def manifest(self, step: int) -> dict:
        return _arrays.read_manifest(self.step_path(step))

    # ---------------- save ----------------
    def save(self, step: int, state, force: bool = False) -> None:
        """Checkpoint `state` (nested dict/list tree of arrays + scalars) as
        `step`. Blocks only for the device->host snapshot; everything else
        is async when async_=True. Raises AsyncCheckpointError here if a
        PREVIOUS background save failed."""
        self._writer._raise_pending()
        sdir = self.step_path(step)
        if is_committed(sdir):
            if not force:
                raise ValueError(
                    f"step {step} already committed in {self.directory} "
                    "(pass force=True to overwrite)")
            self.wait_until_finished()
            if self._proc == 0:
                shutil.rmtree(sdir, ignore_errors=True)
            _sync_processes(f"ckpt_overwrite_{step}")

        t0 = time.perf_counter()
        # unlabelled: a step=N label would grow one registry series per step
        with _tracing.span("ckpt.save.blocking"):
            flat = _arrays.flatten_tree(state)
            snaps: Dict[str, Any] = {
                path: _arrays.snapshot_array(leaf)
                for path, leaf in flat.items()
                if _arrays._is_array_leaf(leaf)
            }
            structure = _arrays._structure(state, snaps)
        blocking = time.perf_counter() - t0
        _metrics.histogram("ckpt.save.blocking_seconds", blocking)

        def write():
            os.makedirs(sdir, exist_ok=True)
            entries = {}
            total = 0
            for path, snap in snaps.items():
                entry = _arrays.write_snapshot(sdir, path, snap)
                total += entry.pop("_bytes_written")
                entries[path] = entry
            manifest = {
                "format": _arrays.FORMAT,
                "step": step,
                "structure": structure,
                "arrays": entries,
                "bytes_written": total,
            }
            self._publish(sdir, step, manifest)
            _metrics.counter("ckpt.save.bytes", total)
            _metrics.histogram("ckpt.save.total_seconds",
                               time.perf_counter() - t0)
            self._gc_old()

        if self.async_:
            self._writer.submit(write)
        else:
            self._writer.run_sync(write)

    def _publish(self, sdir: str, step: int, manifest: dict) -> None:
        """All shard files are on disk -> make the step visible atomically.
        Multi-process: everyone contributes a manifest part, process 0
        merges and writes COMMIT after the barrier proves every process
        finished writing."""
        import jax

        nproc = jax.process_count()
        if nproc > 1:
            _arrays.write_manifest(
                sdir, manifest, manifest_name=f"manifest.part{self._proc}.json")
            _sync_processes(f"ckpt_commit_{step}")
            if self._proc != 0:
                _sync_processes(f"ckpt_committed_{step}")
                return
            parts = []
            for p in range(nproc):
                part_name = f"manifest.part{p}.json"
                parts.append(_arrays.read_manifest(sdir, part_name))
            manifest = _arrays.merge_manifests(parts)
            _arrays.write_manifest(sdir, manifest)
            for p in range(nproc):
                os.remove(os.path.join(sdir, f"manifest.part{p}.json"))
        else:
            _arrays.write_manifest(sdir, manifest)
        self._write_commit(sdir, step)
        if nproc > 1:
            _sync_processes(f"ckpt_committed_{step}")

    def _write_commit(self, sdir: str, step: int) -> None:
        """The atomic publish: rename so a crash can never leave a partial
        COMMIT (a step is either fully visible or fully invisible)."""
        tmp = os.path.join(sdir, COMMIT_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump({"step": step, "time": time.time()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(sdir, COMMIT_NAME))

    # ---------------- restore ----------------
    def restore(self, step: Optional[int] = None, shardings=None,
                live_state=None):
        """Restore a committed step (default: latest). `shardings` is a
        nested tree (or flat {path: NamedSharding} dict) selecting device
        layout per array — on ANY mesh, not just the save-time one; arrays
        without a requested sharding come back as host numpy.

        `live_state` (same structure) lets arrays that are still resident
        on a mesh skip the filesystem: they reshard device-to-device
        through distributed.resharding (bitwise-identical to the file
        path), with shard-file reads as the per-leaf fallback."""
        self.wait_until_finished()
        steps = self.all_steps()
        if step is None:
            if not steps:
                raise FileNotFoundError(
                    f"no committed checkpoint steps in {self.directory}")
            step = steps[-1]
        elif step not in steps:
            raise FileNotFoundError(
                f"step {step} is not a committed checkpoint in "
                f"{self.directory} (committed: {steps})")
        t0 = time.perf_counter()
        # span name distinct from the ckpt.restore.seconds histogram below
        # (span() records a <name>.seconds histogram of its own)
        with _tracing.span("ckpt.restore.load"):
            tree = _arrays.load_tree(self.step_path(step),
                                     shardings=shardings,
                                     validate=self.validate_on_restore,
                                     live_state=live_state)
        _metrics.histogram("ckpt.restore.seconds", time.perf_counter() - t0)
        return tree

    # ---------------- lifecycle ----------------
    def wait_until_finished(self) -> None:
        """Drain in-flight saves; re-raise any background failure."""
        self._writer.wait_until_finished()

    def close(self) -> None:
        self._writer.close()

    # ---------------- GC ----------------
    def _gc_uncommitted(self) -> None:
        """Construction-time sweep: torn saves (no COMMIT) are deleted."""
        if self._proc != 0:
            return
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return
        removed = 0
        for name in names:
            if parse_step(name) is None:
                continue
            path = os.path.join(self.directory, name)
            if os.path.isdir(path) and not is_committed(path):
                shutil.rmtree(path, ignore_errors=True)
                removed += 1
        if removed:
            _metrics.counter("ckpt.gc.uncommitted_removed", removed)

    def _gc_old(self) -> None:
        """keep_last_n sweep over COMMITTED steps; the newest committed step
        is never deleted (keep_last_n <= 0 still keeps one)."""
        if self.keep_last_n is None or self._proc != 0:
            return
        keep = max(1, int(self.keep_last_n))
        steps = self.all_steps()
        removed = 0
        for step in steps[:-keep] if keep < len(steps) else []:
            # remove COMMIT first so a sweep killed mid-rmtree leaves an
            # uncommitted (= invisible, construction-GC'd) directory, not a
            # corrupt committed one
            sdir = self.step_path(step)
            try:
                os.remove(os.path.join(sdir, COMMIT_NAME))
            except FileNotFoundError:
                pass
            shutil.rmtree(sdir, ignore_errors=True)
            removed += 1
        if removed:
            _metrics.counter("ckpt.gc.steps_removed", removed)
