"""Prune, rank, and emit the winning layout as a ShardingContract.

``search_train_step(model, optimizer, mesh=...)`` is the whole loop:

1. build (or borrow) a probe ``ShardedTrainStep`` under the hand-written
   seed layout and trace its step jaxpr ONCE — the jaxpr is
   layout-independent, so every candidate is scored against the same
   trace with nothing compiled;
2. enumerate the deduped candidate space (``space.enumerate_candidates``)
   plus the seed layout itself, always candidate 0;
3. score each candidate (``cost.score_candidate``) and reject
   HBM-infeasible or batch-indivisible layouts outright;
4. rank by predicted step floor (max per-resource roofline), wire bytes
   and HBM pressure as deterministic tie-breaks, the seed winning all
   remaining ties — the searched layout is never predicted-worse than
   the seed by construction.

The winner converts to jax types on demand: ``winner_mesh`` /
``winner_param_specs`` feed straight into
``make_sharded_train_step(..., autoshard=True)`` and
``SearchResult.winner_contract()`` yields the
``analysis.ShardingContract`` the validate stage and the CI gate
re-audit.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from ..observability import attribution
from ..observability import metrics as _metrics
from . import cost as _cost
from . import space as _space

__all__ = [
    "RankedCandidate", "SearchResult", "search_train_step",
    "seed_candidate", "to_partition_spec", "winner_mesh",
    "winner_param_specs",
]


def to_partition_spec(spec: Optional[Tuple[Tuple[str, ...], ...]]):
    """Canonical tuple spec -> jax PartitionSpec."""
    from jax.sharding import PartitionSpec as P

    if not spec:
        return P()
    entries = []
    for e in spec:
        if not e:
            entries.append(None)
        elif len(e) == 1:
            entries.append(e[0])
        else:
            entries.append(tuple(e))
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


@dataclass
class RankedCandidate:
    candidate: _space.Candidate
    cost: _cost.CandidateCost
    rank: int = 0
    is_seed: bool = False

    def row(self) -> Dict[str, Any]:
        """One ranked-table row: everything the CLI/bench print."""
        return {
            "rank": self.rank,
            "layout": self.candidate.name,
            "family": self.candidate.family,
            "mesh": {a: n for a, n in self.candidate.mesh_axes if n > 1},
            "seed": self.is_seed,
            "floor_ms": round(self.cost.floor_ms, 6),
            "floors_ms": {k: round(v, 6)
                          for k, v in self.cost.floors_ms.items()},
            "binding": self.cost.binding,
            "wire_bytes_per_device": round(
                self.cost.wire_bytes_per_device, 1),
            "hbm_fit_bytes": int(self.cost.hbm_fit_bytes),
            "fits": self.cost.fits,
            "compute_split": self.cost.compute_split,
            "n_events": self.cost.n_events,
            "predicted_families": dict(sorted(
                self.cost.predicted_families.items())),
        }


@dataclass
class SearchResult:
    ranked: List[RankedCandidate] = field(default_factory=list)
    rejected: List[Tuple[str, str]] = field(default_factory=list)
    hw_name: str = ""
    device_count: int = 0
    batch_shape: Tuple[int, ...] = ()
    search_seconds: float = 0.0
    flat_totals: Dict[str, float] = field(default_factory=dict)

    @property
    def winner(self) -> Optional[RankedCandidate]:
        return self.ranked[0] if self.ranked else None

    @property
    def seed(self) -> Optional[RankedCandidate]:
        for rc in self.ranked:
            if rc.is_seed:
                return rc
        return None

    def table(self) -> List[Dict[str, Any]]:
        return [rc.row() for rc in self.ranked]

    def as_dict(self) -> Dict[str, Any]:
        return {
            "hw": self.hw_name,
            "device_count": self.device_count,
            "batch_shape": list(self.batch_shape),
            "search_seconds": round(self.search_seconds, 3),
            "candidates": len(self.ranked),
            "rejected": [{"layout": n, "reason": r}
                         for n, r in self.rejected],
            "winner": (self.winner.row() if self.winner else None),
            "table": self.table(),
        }

    def winner_contract(self, probe) -> Any:
        """The winner as an ``analysis.ShardingContract`` — built by
        re-deriving the step's in/out shardings under the winning layout
        (what ``ShardedTrainStep`` would jit with)."""
        win = self.winner
        if win is None or win.is_seed:
            return probe.sharding_contract()
        import numpy as _np

        from ..distributed.fleet.utils import make_sharded_train_step

        st = make_sharded_train_step(
            probe.model, probe.optimizer,
            mesh=winner_mesh(win.candidate),
            param_specs=winner_param_specs(win.candidate))
        return st.sharding_contract()


def winner_mesh(candidate: _space.Candidate, devices=None):
    """The candidate's mesh over the physical devices."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    names = tuple(a for a, _n in candidate.mesh_axes)
    shape = tuple(n for _a, n in candidate.mesh_axes)
    world = 1
    for n in shape:
        world *= n
    return Mesh(np.asarray(devices[:world]).reshape(shape), names)


def winner_param_specs(candidate: _space.Candidate) -> Dict[str, Any]:
    """{param name: PartitionSpec} for ``ShardedTrainStep(param_specs=)``."""
    return {name: to_partition_spec(spec)
            for name, spec in candidate.param_specs}


def seed_candidate(probe) -> _space.Candidate:
    """The hand-written layout (the probe step's actual param shardings)
    expressed as a Candidate, so it ranks in the same table."""
    from ..analysis.sharding_flow import spec_of

    mesh = probe.mesh
    mesh_axes = tuple(zip(mesh.axis_names, mesh.devices.shape))
    sizes = dict(mesh_axes)
    table = []
    for name, sh in sorted(probe._p_shard.items()):
        ndim = len(probe.params[name].shape)
        spec = spec_of(sh, ndim)
        table.append((name, spec if spec is not None
                      else tuple(() for _ in range(ndim))))
    batch_axes = tuple(a for a in _space.DATA_AXES
                       if int(sizes.get(a, 1)) > 1)
    mesh_name = ".".join(f"{a}{n}" for a, n in mesh_axes if n > 1) \
        or "single"
    return _space.Candidate(name=f"{mesh_name}/seed", family="seed",
                            mesh_axes=mesh_axes,
                            param_specs=tuple(table),
                            batch_axes=batch_axes)


def _state_degrees(probe, candidate: _space.Candidate,
                   shard_axis: Optional[str]) -> Dict[str, int]:
    """Shard degree of each param's optimizer state under the candidate:
    the param's own degree, times the ZeRO axis when it is free (the
    fleet ``_state_sharding_like`` placement)."""
    sizes = candidate.axis_sizes()
    out: Dict[str, int] = {}
    for name, spec in candidate.param_specs:
        deg = _cost.shard_degree(spec, sizes)
        if shard_axis:
            z = int(sizes.get(shard_axis, 1))
            used = {a for e in (spec or ()) for a in e}
            if z > 1 and shard_axis not in used:
                shape = tuple(probe.params[name].shape)
                if any((not e) and d % z == 0 and d >= z
                       for e, d in zip(
                           (spec or tuple(() for _ in shape)), shape)):
                    deg *= z
        out[name] = deg
    return out


def _candidate_in_specs(probe, candidate: _space.Candidate, args) -> List:
    """Flat canonical arg specs for the step signature under the
    candidate — params from the table, optimizer state through the fleet
    ZeRO placement, batch over the candidate's data axes, everything
    else replicated."""
    import jax

    from ..analysis import sharding_flow as _sf

    sizes = candidate.axis_sizes()
    zero_axis = getattr(probe.optimizer, "_shard_state_axis", None) \
        or "sharding"
    specs_by_name = dict(candidate.param_specs)

    def param_spec(name: str, ndim: int):
        spec = specs_by_name.get(name)
        if spec is None:
            spec = tuple(() for _ in range(ndim))
        return tuple(spec) + tuple(() for _ in range(ndim - len(spec)))

    def state_spec(name: str, leaf) -> Tuple[Tuple[str, ...], ...]:
        # moments shaped like the param inherit its spec; anything else
        # (step counters etc.) starts replicated — then the ZeRO axis
        # takes the first free divisible dim (fleet _state_sharding_like)
        shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
        if not shape:
            return ()
        pshape = tuple(int(d) for d in probe.params[name].shape)
        base = list(param_spec(name, len(shape))) if shape == pshape \
            else [()] * len(shape)
        z = int(sizes.get(zero_axis, 1))
        used = {a for e in base for a in e}
        if z > 1 and zero_axis not in used:
            for i, e in enumerate(base):
                if not e and shape[i] % z == 0 and shape[i] >= z:
                    base[i] = (zero_axis,)
                    break
        return tuple(base)

    batch_entry = tuple(a for a in candidate.batch_axes
                        if int(sizes.get(a, 1)) > 1)
    params, opt_state, buffers, ef, x, y, lr, seed = args[:8]

    flat: List = []
    for name in sorted(params):  # dict flatten order is sorted keys
        flat.append(param_spec(name, len(params[name].shape)))
    for name in sorted(opt_state):
        leaves = jax.tree_util.tree_leaves(opt_state[name])
        flat.extend(state_spec(name, leaf) for leaf in leaves)
    flat.extend(_sf.REPLICATED(len(getattr(leaf, "shape", ())))
                for leaf in jax.tree_util.tree_leaves(buffers))
    flat.extend(_sf.REPLICATED(len(getattr(leaf, "shape", ())))
                for leaf in jax.tree_util.tree_leaves(ef))
    for arr in (x, y):
        nd = len(arr.shape)
        flat.append(((batch_entry,) if batch_entry else ((),))
                    + tuple(() for _ in range(nd - 1)))
    flat.append(())   # lr
    flat.append(())   # seed
    if getattr(probe, "_health", False):
        import numpy as np
        flat.append(_sf.REPLICATED(np.ndim(probe._health_poison)))
    return flat


def search_train_step(model=None, optimizer=None, mesh=None,
                      batch_shape: Optional[Tuple[int, int]] = None,
                      hw: Optional[attribution.HardwareSpec] = None,
                      families: Optional[Sequence[str]] = None,
                      probe=None,
                      axis_names: Sequence[str] = _space.AXIS_NAMES,
                      fixed_mesh: bool = False,
                      ) -> SearchResult:
    """Run the full layout search for a training step. Either pass a
    ``probe`` (an existing ShardedTrainStep under the seed layout) or
    ``model``+``optimizer`` (+``mesh``) for the search to build one.

    ``fixed_mesh=True`` searches only the rule-table dimension: every
    candidate keeps the probe's mesh factorization (what the elastic
    supervisor needs — it owns the mesh, the search owns the layout)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..observability import anatomy as _anatomy

    t0 = time.perf_counter()
    if probe is None:
        if model is None or optimizer is None:
            raise ValueError("search_train_step needs a probe step or "
                             "model+optimizer")
        from ..distributed.fleet.utils import make_sharded_train_step
        probe = make_sharded_train_step(model, optimizer, mesh=mesh)
    if probe._pp > 1:
        raise ValueError("autoshard does not search pipeline layouts "
                         "(pp>1); shard the pp mesh by hand")
    if probe.scaler_state is not None:
        raise ValueError("autoshard does not model the loss-scaler step "
                         "signature; search without a scaler")

    ndev = probe.mesh.devices.size
    if batch_shape is None:
        batch_shape = (2 * ndev, 16)
    bsz, seq = int(batch_shape[0]), int(batch_shape[1])
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, 16, size=(bsz, seq), dtype=np.int32))
    y = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))

    closed = probe.step_jaxpr(x, y)
    args = (probe.params, probe.opt_state, probe.buffers, probe.ef_state,
            x, y, jnp.float32(1e-3), jnp.uint32(0))

    if hw is None:
        hw = attribution.hardware_for_backend(jax.default_backend())

    flat = _anatomy.flat_costs(closed.jaxpr)
    flat_totals = {"flops": float(flat.get("flops", 0.0)),
                   "hbm_bytes": float(flat.get("hbm_bytes", 0.0))}

    param_bytes = {
        name: int(np.prod(arr.shape, dtype=np.int64))
        * np.dtype(arr.dtype).itemsize
        for name, arr in probe.params.items()}
    state_bytes = {
        name: sum(int(np.prod(l.shape, dtype=np.int64))
                  * np.dtype(l.dtype).itemsize
                  for l in jax.tree_util.tree_leaves(probe.opt_state[name]))
        for name in probe.opt_state}
    shard_axis = getattr(probe.optimizer, "_shard_state_axis", None)

    shapes = {name: tuple(arr.shape) for name, arr in probe.params.items()}
    seed = seed_candidate(probe)
    enumerated = _space.enumerate_candidates(
        shapes, ndev, axis_names=axis_names, families=families,
        batch_divisor=bsz)
    if fixed_mesh:
        want = {a: n for a, n in seed.mesh_axes if int(n) > 1}
        enumerated = [
            c for c in enumerated
            if {a: n for a, n in c.mesh_axes if int(n) > 1} == want]
    candidates = [seed] + [c for c in enumerated
                           if c.signature() != seed.signature()]

    scored: List[RankedCandidate] = []
    rejected: List[Tuple[str, str]] = []
    for i, cand in enumerate(candidates):
        try:
            in_specs = _candidate_in_specs(probe, cand, args)
            c = _cost.score_candidate(
                closed, in_specs, cand, hw, flat_totals, param_bytes,
                state_bytes, _state_degrees(probe, cand, shard_axis),
                path=f"autoshard/{cand.name}")
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            rejected.append((cand.name, f"{type(e).__name__}: {e}"))
            continue
        if not c.fits:
            rejected.append((cand.name,
                             f"HBM fit {c.hbm_fit_bytes / 1e9:.2f} GB "
                             f"exceeds {c.hbm_capacity_bytes / 1e9:.0f} GB"))
            continue
        scored.append(RankedCandidate(candidate=cand, cost=c,
                                      is_seed=(i == 0)))

    # seed-first stable sort: ties go to the hand-written layout
    scored.sort(key=lambda rc: (
        round(rc.cost.floor_ms, 9),
        round(rc.cost.wire_bytes_per_device, 3),
        round(rc.cost.hbm_fit_bytes, 1),
        not rc.is_seed,
        rc.candidate.name))
    for r, rc in enumerate(scored):
        rc.rank = r

    dt = time.perf_counter() - t0
    result = SearchResult(
        ranked=scored, rejected=rejected, hw_name=hw.name,
        device_count=ndev, batch_shape=(bsz, seq), search_seconds=dt,
        flat_totals=flat_totals)

    _metrics.gauge("autoshard.candidates", len(scored))
    _metrics.gauge("autoshard.rejected", len(rejected))
    _metrics.histogram("autoshard.search_ms", dt * 1e3)
    if result.winner is not None:
        _metrics.gauge("autoshard.winner_floor_ms",
                       result.winner.cost.floor_ms)
        _metrics.gauge("autoshard.winner_is_seed",
                       1.0 if result.winner.is_seed else 0.0)
    return result
