"""Sharding auto-search: enumerate candidate layouts, score them without
compiling, rank, and validate the winners through the HLO audit.

Lazy imports keep ``space``/``cost`` importable without jax (the CLI's
synthetic-package mode); the jax-touching stages live in ``search`` and
``validate``.
"""

from . import cost, space

__all__ = ["cost", "search", "search_train_step", "space", "validate",
           "validate_top_k"]


def __getattr__(name):
    import importlib

    if name in ("search", "search_train_step", "winner_mesh",
                "winner_param_specs", "seed_candidate", "SearchResult",
                "RankedCandidate"):
        mod = importlib.import_module(".search", __name__)
        return mod if name == "search" else getattr(mod, name)
    if name in ("validate", "validate_top_k", "CandidateValidation"):
        mod = importlib.import_module(".validate", __name__)
        return mod if name == "validate" else getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
