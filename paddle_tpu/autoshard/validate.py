"""Compile the top-k searched layouts through ``analysis.hlo_audit`` and
reconcile the cost model against the partitioned program.

The search never compiles; this stage is where its predictions meet XLA.
For each of the top-k ranked candidates a real ``ShardedTrainStep`` is
built under the candidate's mesh + param table, wrapped in the same
``ProgramSpec`` shape the analysis corpus uses for ``train_step``, and
run through ``hlo_audit.audit_spec``. A candidate validates when:

- the audit compiles clean (no error),
- **zero unexplained collective families** — every family XLA emitted at
  >=256 KiB was predicted by the flow model under the candidate's specs
  (hlo_audit's own threshold),
- the predicted per-device wire bytes agree with the audited per-device
  wire bytes within ``WIRE_FACTOR``x — the same 2.0x factor the
  analyzer's ``SiteContract.wire_tolerance`` uses for model-vs-plan
  reconciliation,
- the compiled peak HBM fits the device capacity the cost model gated on
  (the analytic fit estimate exists to reject OOM layouts; the compiled
  peak is the truth it is calibrated against, reported as a ratio).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import cost as _cost
from .search import SearchResult, winner_mesh, winner_param_specs

__all__ = ["CandidateValidation", "WIRE_FACTOR", "WIRE_MIN_BYTES",
           "validate_top_k"]

#: multiplicative agreement factor for predicted vs audited wire bytes —
#: analyzer SiteContract.wire_tolerance's convention
WIRE_FACTOR = 2.0

#: below this, both accountings are in fusion-noise territory — agree
#: trivially (hlo_audit's unexplained threshold)
WIRE_MIN_BYTES = 256 * 1024


@dataclass
class CandidateValidation:
    layout: str
    rank: int
    is_seed: bool
    error: Optional[str] = None
    unexplained: List[str] = field(default_factory=list)
    predicted_wire: float = 0.0
    actual_wire: int = 0
    wire_ratio: Optional[float] = None
    wire_ok: bool = False
    predicted_families: Dict[str, int] = field(default_factory=dict)
    actual_counts: Dict[str, int] = field(default_factory=dict)
    hbm_fit_bytes: float = 0.0
    hbm_peak_bytes: int = 0
    hbm_ratio: Optional[float] = None
    hbm_ok: bool = False
    compile_seconds: float = 0.0

    @property
    def ok(self) -> bool:
        return (self.error is None and not self.unexplained
                and self.wire_ok and self.hbm_ok)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "layout": self.layout, "rank": self.rank, "seed": self.is_seed,
            "ok": self.ok, "error": self.error,
            "unexplained": list(self.unexplained),
            "predicted_wire": round(self.predicted_wire, 1),
            "actual_wire": self.actual_wire,
            "wire_ratio": (round(self.wire_ratio, 3)
                           if self.wire_ratio is not None else None),
            "wire_ok": self.wire_ok,
            "actual_counts": dict(sorted(self.actual_counts.items())),
            "hbm_fit_bytes": int(self.hbm_fit_bytes),
            "hbm_peak_bytes": self.hbm_peak_bytes,
            "hbm_ratio": (round(self.hbm_ratio, 3)
                          if self.hbm_ratio is not None else None),
            "hbm_ok": self.hbm_ok,
            "compile_seconds": round(self.compile_seconds, 3),
        }


def _step_for(candidate, probe, ranked):
    if ranked.is_seed:
        return probe
    from ..distributed.fleet.utils import make_sharded_train_step

    return make_sharded_train_step(
        probe.model, probe.optimizer,
        mesh=winner_mesh(candidate),
        param_specs=winner_param_specs(candidate))


def validate_top_k(result: SearchResult, probe, k: int = 3
                   ) -> List[CandidateValidation]:
    """Audit the top-k ranked candidates. ``probe`` is the seed
    ShardedTrainStep the search traced (reused for the seed row so it is
    audited exactly as built)."""
    import numpy as np
    import jax.numpy as jnp

    from ..analysis.analyzer import ProgramSpec, SiteContract
    from ..analysis import hlo_audit as _hlo

    bsz, seq = result.batch_shape
    rng = np.random.RandomState(0)
    x = rng.randint(0, 16, size=(bsz, seq))
    y = np.roll(x, -1, axis=1)

    out: List[CandidateValidation] = []
    for rc in result.ranked[:max(int(k), 1)]:
        v = CandidateValidation(layout=rc.candidate.name, rank=rc.rank,
                                is_seed=rc.is_seed,
                                predicted_families=dict(
                                    rc.cost.predicted_families),
                                hbm_fit_bytes=rc.cost.hbm_fit_bytes)
        try:
            st = _step_for(rc.candidate, probe, rc)
        except Exception as e:  # noqa: BLE001 - recorded on the row
            v.error = f"{type(e).__name__}: {e}"
            out.append(v)
            continue
        spec = ProgramSpec(
            f"autoshard/{rc.candidate.name}", st._compiled_step_fn,
            (st.params, st.opt_state, st.buffers, st.ef_state,
             jnp.asarray(x), jnp.asarray(y), jnp.float32(1e-3),
             jnp.uint32(0)),
            SiteContract(one_compile=True, donate_argnums=(0, 1, 2, 3)),
            sharding=st.sharding_contract())
        audit = _hlo.audit_spec(spec)
        v.error = audit.error
        v.unexplained = list(audit.unexplained)
        v.actual_wire = int(audit.wire_bytes)
        v.actual_counts = dict(audit.counts)
        v.compile_seconds = audit.compile_seconds
        v.predicted_wire = float(rc.cost.wire_bytes_per_device)

        lo = min(v.predicted_wire, float(v.actual_wire))
        hi = max(v.predicted_wire, float(v.actual_wire))
        if hi < WIRE_MIN_BYTES:
            v.wire_ok, v.wire_ratio = True, None
        else:
            v.wire_ratio = hi / max(lo, 1.0)
            v.wire_ok = v.wire_ratio <= WIRE_FACTOR

        v.hbm_peak_bytes = int(audit.hbm.get("peak", 0))
        cap = rc.cost.hbm_capacity_bytes
        if v.hbm_peak_bytes and v.hbm_fit_bytes:
            v.hbm_ratio = v.hbm_peak_bytes / v.hbm_fit_bytes
        v.hbm_ok = (cap is None or v.hbm_peak_bytes <= cap)
        out.append(v)
    return out
