"""Compile-free candidate scoring.

Three ingredients, all reused from the analyzers rather than re-derived:

- **wire bytes** — ``analysis.sharding_flow`` propagates the candidate's
  arg specs through the (layout-independent) train-step jaxpr once per
  candidate; every FlowEvent converts to per-device receive-side bytes
  with ``hlo_audit``'s own ring conventions (all-reduce ``2(n-1)b/n``,
  all-gather/replicate ``(n-1)b/n``, reshard modeled as an all-to-all of
  the per-device shard). The group size ``n`` is the product of the
  event's mesh axes (``FlowEvent.axes``).
- **roofline floors** — per-device FLOPs and HBM traffic are the
  jaxpr's flat totals (``observability.anatomy.flat_costs``) divided by
  the candidate's compute split (the data-axis product, times ``mp``
  when the table actually shards matmul weights over it), then run
  through ``observability.attribution.floors``.
- **HBM fit** — an analytic per-device residency estimate: params +
  grads + fp32 master + optimizer moments (each divided by its spec's
  shard degree) + the activation working set (global activation traffic
  scaled by ``ACT_RESIDENT_FRACTION`` and the compute split). A
  candidate whose estimate exceeds the device HBM capacity is rejected
  outright, never ranked.

Scores are fully deterministic: same jaxpr + same candidate -> same
floors, which is what lets the bench A/B row and the contract tests
reconcile against the search exactly on the cpu-nominal profile.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from ..observability import attribution

__all__ = [
    "ACT_RESIDENT_FRACTION", "CandidateCost", "HBM_CAPACITY_BYTES",
    "compute_split", "event_wire_bytes", "hbm_fit_bytes", "score_candidate",
    "shard_degree",
]

#: per-device HBM capacity by attribution.HardwareSpec name; the
#: cpu-nominal figure is a stand-in host budget so tiny CPU corpora
#: never reject, v5e is the real 16G part
HBM_CAPACITY_BYTES: Dict[str, float] = {
    "tpu-v5e": 16e9,
    "cpu-nominal": 64e9,
}

#: fraction of the (already compute-split) activation HBM traffic
#: assumed live at the peak — a documented modeling constant, not a
#: measurement; the validate stage reconciles it against the compiled
#: program's true peak
ACT_RESIDENT_FRACTION = 0.25


@dataclass
class CandidateCost:
    """Everything the ranker and the bench row need about one candidate."""

    flops_per_device: float
    hbm_bytes_per_device: float
    wire_bytes_per_device: float
    wire_by_scope: Dict[str, float]
    floors_ms: Dict[str, float]
    floor_ms: float
    binding: str
    compute_split: int
    hbm_fit_bytes: float
    hbm_capacity_bytes: Optional[float]
    fits: bool
    n_events: int
    predicted_families: Dict[str, int]  # family -> global bytes (audit conv)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "flops_per_device": self.flops_per_device,
            "hbm_bytes_per_device": self.hbm_bytes_per_device,
            "wire_bytes_per_device": round(self.wire_bytes_per_device, 1),
            "wire_by_scope": {k: round(v, 1)
                              for k, v in sorted(self.wire_by_scope.items())},
            "floors_ms": {k: round(v, 6)
                          for k, v in self.floors_ms.items()},
            "floor_ms": round(self.floor_ms, 6),
            "binding": self.binding,
            "compute_split": self.compute_split,
            "hbm_fit_bytes": int(self.hbm_fit_bytes),
            "fits": self.fits,
            "n_events": self.n_events,
            "predicted_families": dict(sorted(
                self.predicted_families.items())),
        }


def _group(axes: Iterable[str], axis_sizes: Mapping[str, int],
           world: int) -> int:
    n = 1
    for a in axes:
        n *= int(axis_sizes.get(a, 1))
    if n <= 1:
        # events recorded before axes were threaded through (or an axis
        # the mesh doesn't size): conservatively the whole mesh
        return max(int(world), 1)
    return n


def event_wire_bytes(event: Any, axis_sizes: Mapping[str, int],
                     world: Optional[int] = None) -> float:
    """Per-device receive-side bytes for one FlowEvent — the repo's plan
    convention, mirroring ``hlo_audit.HloCollective.wire_bytes``."""
    if world is None:
        world = 1
        for n in axis_sizes.values():
            world *= int(n)
    b = float(event.nbytes)
    n = _group(getattr(event, "axes", ()), axis_sizes, world)
    if n <= 1:
        return 0.0
    if event.kind == "all-reduce":
        return 2.0 * (n - 1) * b / n
    if event.kind in ("all-gather", "replicate"):
        return (n - 1) * b / n
    if event.kind == "reshard":  # all-to-all of the per-device shard
        return (n - 1) * b / (n * n)
    return b


#: FlowEvent kind -> HLO collective family (hlo_audit's own mapping)
KIND_FAMILY = {
    "all-reduce": "all-reduce",
    "all-gather": "all-gather",
    "replicate": "all-gather",
    "reshard": "all-to-all",
}


def shard_degree(spec: Optional[Tuple[Tuple[str, ...], ...]],
                 axis_sizes: Mapping[str, int]) -> int:
    """How many ways a tensor with this canonical spec is split."""
    if not spec:
        return 1
    deg = 1
    for entry in spec:
        for a in entry:
            deg *= int(axis_sizes.get(a, 1))
    return max(deg, 1)


def compute_split(param_specs: Iterable[Tuple[str, Tuple]],
                  batch_axes: Iterable[str],
                  axis_sizes: Mapping[str, int],
                  model_axes: Tuple[str, ...] = ("mp",)) -> int:
    """How many ways the step's FLOPs divide: the data-axis product
    always (the batch is split), times each model axis the table
    actually shards a >=2-dim param over (tensor parallelism splits the
    matmuls; the fsdp axis does NOT split compute — params are gathered
    back for the mathmuls, which the wire model charges for)."""
    split = 1
    for a in batch_axes:
        split *= int(axis_sizes.get(a, 1))
    used_model = set()
    for _name, spec in param_specs:
        if spec and len(spec) >= 2:
            for entry in spec:
                used_model.update(a for a in entry if a in model_axes)
    for a in used_model:
        split *= int(axis_sizes.get(a, 1))
    return max(split, 1)


def hbm_fit_bytes(param_bytes: Mapping[str, int],
                  param_specs: Mapping[str, Tuple],
                  state_bytes: Mapping[str, int],
                  state_degrees: Mapping[str, int],
                  axis_sizes: Mapping[str, int],
                  act_bytes_global: float,
                  split: int,
                  master_bytes_per_elem: float = 0.0,
                  ) -> float:
    """Analytic per-device residency: params + grads (same placement) +
    optional fp32 master + moments + the activation working set."""
    total = 0.0
    for name, nbytes in param_bytes.items():
        deg = shard_degree(param_specs.get(name), axis_sizes)
        per = nbytes / deg
        total += 2.0 * per  # param + grad
        if master_bytes_per_elem:
            total += per * master_bytes_per_elem
    for name, nbytes in state_bytes.items():
        total += nbytes / max(int(state_degrees.get(name, 1)), 1)
    total += ACT_RESIDENT_FRACTION * act_bytes_global / max(split, 1)
    return total


def score_candidate(closed: Any,
                    in_specs: List,
                    candidate: Any,
                    hw: "attribution.HardwareSpec",
                    flat_totals: Mapping[str, float],
                    param_bytes: Mapping[str, int],
                    state_bytes: Mapping[str, int],
                    state_degrees: Mapping[str, int],
                    path: str = "autoshard") -> CandidateCost:
    """Score one candidate against the traced step. ``in_specs`` are the
    flat canonical arg specs for THIS candidate; ``flat_totals`` the
    layout-independent jaxpr totals ({flops, hbm_bytes})."""
    from ..analysis import sharding_flow as _sf

    axis_sizes = candidate.axis_sizes()
    world = 1
    for _a, n in candidate.mesh_axes:
        world *= int(n)

    result = _sf.propagate_jaxpr(closed, in_specs, axis_sizes, path)

    wire = 0.0
    by_scope: Dict[str, float] = {}
    families: Dict[str, int] = {}
    for ev in result.events:
        w = event_wire_bytes(ev, axis_sizes, world)
        wire += w
        scope = ev.scope or "unattributed"
        by_scope[scope] = by_scope.get(scope, 0.0) + w
        fam = KIND_FAMILY.get(ev.kind)
        if fam:
            families[fam] = families.get(fam, 0) + int(ev.nbytes)

    split = compute_split(candidate.param_specs, candidate.batch_axes,
                          axis_sizes)
    flops_dev = float(flat_totals.get("flops", 0.0)) / split
    hbm_dev = float(flat_totals.get("hbm_bytes", 0.0)) / split

    floors_s = attribution.floors(hw, flops_dev, hbm_dev, wire)
    floors = {r: s * 1e3 for r, s in floors_s.items()}
    binding, floor_ms = "compute", 0.0
    for r in attribution.RESOURCES:  # deterministic tie-break
        if r in floors and floors[r] > floor_ms:
            binding, floor_ms = r, floors[r]

    fit = hbm_fit_bytes(param_bytes, dict(candidate.param_specs),
                        state_bytes, state_degrees, axis_sizes,
                        float(flat_totals.get("hbm_bytes", 0.0)), split)
    cap = HBM_CAPACITY_BYTES.get(hw.name)
    fits = True if cap is None else fit <= cap

    return CandidateCost(
        flops_per_device=flops_dev, hbm_bytes_per_device=hbm_dev,
        wire_bytes_per_device=wire, wire_by_scope=by_scope,
        floors_ms=floors, floor_ms=floor_ms, binding=binding,
        compute_split=split, hbm_fit_bytes=fit, hbm_capacity_bytes=cap,
        fits=fits, n_events=len(result.events),
        predicted_families=families)
