"""Candidate layout enumeration for the sharding auto-search.

Stdlib-only by design (no jax import): the search space is pure data —
mesh factorizations of the physical device count into the hybrid axes
(dp / sharding / mp), and regex rule tables mapping parameter names to
partition specs (the ``match_partition_rules`` idiom). Specs use the
analyzer's canonical ShardSpec form — one tuple of mesh-axis names per
tensor dim, ``()`` meaning replicated on that dim — so candidates can be
scored by ``analysis.sharding_flow`` without materializing a single
``NamedSharding``. ``search.py`` converts the winner to jax types.

Families:

- ``replicated``    pure data parallelism — every param replicated
- ``megatron``      tensor parallelism over ``mp`` (column/row splits +
                    vocab-parallel embedding, the models' own dist_spec
                    convention)
- ``fsdp``          ZeRO-3 style — every param sharded over the
                    ``sharding`` axis on its first divisible dim
- ``megatron_fsdp`` both: mp splits first, the sharding axis takes the
                    first remaining free divisible dim

Resolution sanitizes every spec against the candidate's axis sizes: an
axis of size 1 disappears, a dim not divisible by its axis degree falls
back to replicated, and two rules can never place the same axis twice.
Dedup is by ``Candidate.signature()`` — the resolved table plus the
sizes of the axes it actually uses — so e.g. ``megatron`` on an mp=1
factorization collapses into ``replicated`` and is emitted once.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "AXIS_NAMES", "Candidate", "LayoutRule", "RULE_FAMILIES",
    "enumerate_candidates", "match_partition_rules", "mesh_factorizations",
    "resolve_table",
]

#: the hybrid-parallel axes the search factorizes the device count over;
#: ``sharding`` is the ZeRO/fsdp axis and also a data axis (fleet
#: convention: the batch is sharded over dp AND sharding AND ep)
AXIS_NAMES: Tuple[str, ...] = ("dp", "sharding", "mp")

#: data axes (batch dim 0) — mirror of sharding_utils.DATA_AXES minus ep
DATA_AXES: Tuple[str, ...] = ("dp", "sharding")

#: sentinel spec: shard the first free divisible dim over the fsdp axis
FSDP_AUTO = "fsdp-auto"

Spec = Tuple[Tuple[str, ...], ...]


@dataclass(frozen=True)
class LayoutRule:
    """One regex row of a rule table: first match wins."""

    pattern: str
    #: a canonical Spec, or FSDP_AUTO
    spec: object

    def matches(self, name: str) -> bool:
        return re.search(self.pattern, name) is not None


def _meg(*entries) -> Spec:
    return tuple(tuple(e) if isinstance(e, (tuple, list)) else
                 ((e,) if e else ()) for e in entries)


#: family name -> rule table (regexes follow the models' naming:
#: VocabParallelEmbedding / ColumnParallel qkv+fc1 / RowParallel proj+fc2)
RULE_FAMILIES: Dict[str, Tuple[LayoutRule, ...]] = {
    "replicated": (
        LayoutRule(r".*", ()),
    ),
    "megatron": (
        LayoutRule(r"word_embeddings\.weight$", _meg("mp", None)),
        LayoutRule(r"(qkv|fc1)\.weight$", _meg(None, "mp")),
        LayoutRule(r"(qkv|fc1)\.bias$", _meg("mp")),
        LayoutRule(r"(proj|fc2)\.weight$", _meg("mp", None)),
        LayoutRule(r".*", ()),
    ),
    "fsdp": (
        LayoutRule(r".*", FSDP_AUTO),
    ),
    "megatron_fsdp": (
        LayoutRule(r"word_embeddings\.weight$", _meg("mp", None)),
        LayoutRule(r"(qkv|fc1)\.weight$", _meg(None, "mp")),
        LayoutRule(r"(qkv|fc1)\.bias$", _meg("mp")),
        LayoutRule(r"(proj|fc2)\.weight$", _meg("mp", None)),
        LayoutRule(r".*", FSDP_AUTO),
    ),
}


@dataclass(frozen=True)
class Candidate:
    """One fully resolved layout candidate."""

    name: str                              # "dp2.sharding2.mp2/megatron"
    family: str
    mesh_axes: Tuple[Tuple[str, int], ...]  # ordered (axis, size), all axes
    param_specs: Tuple[Tuple[str, Spec], ...]  # sorted (name, spec)
    batch_axes: Tuple[str, ...]            # axes sharding batch dim 0

    def axis_sizes(self) -> Dict[str, int]:
        return dict(self.mesh_axes)

    def spec_of(self, name: str) -> Optional[Spec]:
        for n, s in self.param_specs:
            if n == name:
                return s
        return None

    def used_axes(self) -> Tuple[str, ...]:
        used = set(self.batch_axes)
        for _n, spec in self.param_specs:
            for entry in spec:
                used.update(entry)
        return tuple(sorted(used))

    def signature(self) -> Tuple:
        """Canonical dedup key: the resolved table + batch placement +
        the sizes of the axes actually used. Everything the cost model
        can see; factorizations differing only in unused axes collapse."""
        sizes = self.axis_sizes()
        return (self.param_specs, self.batch_axes,
                tuple((a, sizes[a]) for a in self.used_axes()))


def mesh_factorizations(ndev: int,
                        axis_names: Sequence[str] = AXIS_NAMES
                        ) -> List[Tuple[Tuple[str, int], ...]]:
    """Every ordered factorization of ``ndev`` over ``axis_names``."""
    names = tuple(axis_names)
    out: List[Tuple[Tuple[str, int], ...]] = []

    def rec(i: int, rest: int, acc: Tuple[int, ...]):
        if i == len(names) - 1:
            out.append(tuple(zip(names, acc + (rest,))))
            return
        d = 1
        while d <= rest:
            if rest % d == 0:
                rec(i + 1, rest // d, acc + (d,))
            d += 1

    rec(0, max(int(ndev), 1), ())
    return out


def match_partition_rules(rules: Sequence[LayoutRule], name: str):
    """First matching rule's spec (the SNIPPETS idiom); no match raises."""
    for rule in rules:
        if rule.matches(name):
            return rule.spec
    raise ValueError(f"no partition rule matches parameter {name!r}")


def _sanitize(spec: Spec, shape: Tuple[int, ...],
              sizes: Mapping[str, int]) -> Spec:
    """Clamp a spec template to a shape under concrete axis sizes: axes
    of size 1 vanish, non-divisible placements fall back to replicated,
    and no axis is used twice."""
    entries: List[Tuple[str, ...]] = []
    used: set = set()
    for d in range(len(shape)):
        entry = spec[d] if d < len(spec) else ()
        kept: List[str] = []
        deg = 1
        for a in entry:
            n = int(sizes.get(a, 1))
            if n <= 1 or a in used:
                continue
            if shape[d] % (deg * n) == 0:
                kept.append(a)
                used.add(a)
                deg *= n
        entries.append(tuple(kept))
    return tuple(entries)


def resolve_table(rules: Sequence[LayoutRule],
                  shapes: Mapping[str, Tuple[int, ...]],
                  sizes: Mapping[str, int],
                  fsdp_axis: str = "sharding"
                  ) -> Dict[str, Spec]:
    """Resolve a rule table against concrete shapes and axis sizes."""
    return {name: _resolve_param(rules, name, shape, sizes, fsdp_axis)
            for name, shape in shapes.items()}


def _place_fsdp(spec: Spec, shape: Tuple[int, ...], fsdp_axis: str,
                deg: int) -> Spec:
    """Add the fsdp axis on the first free dim divisible by its degree
    (mirror of fleet's ``_state_sharding_like`` placement)."""
    if deg <= 1:
        return spec
    used = {a for e in spec for a in e}
    if fsdp_axis in used:
        return spec
    entries = list(spec)
    for i, e in enumerate(entries):
        if not e and shape[i] % deg == 0 and shape[i] >= deg:
            entries[i] = (fsdp_axis,)
            break
    return tuple(entries)


def _resolve_param(rules: Sequence[LayoutRule], name: str,
                   shape: Tuple[int, ...], sizes: Mapping[str, int],
                   fsdp_axis: str) -> Spec:
    shape = tuple(int(d) for d in shape)
    if not shape:
        return ()
    template = match_partition_rules(rules, name)
    if template == FSDP_AUTO:
        base: Spec = tuple(() for _ in shape)
        fsdp = True
    else:
        base = _sanitize(tuple(template), shape, sizes)
        fsdp = any(r.spec == FSDP_AUTO for r in rules if r.matches(name))
    if fsdp:
        base = _place_fsdp(base, shape, fsdp_axis,
                           int(sizes.get(fsdp_axis, 1)))
    return base


def enumerate_candidates(shapes: Mapping[str, Tuple[int, ...]],
                         ndev: int,
                         axis_names: Sequence[str] = AXIS_NAMES,
                         families: Optional[Sequence[str]] = None,
                         fsdp_axis: str = "sharding",
                         batch_divisor: Optional[int] = None
                         ) -> List[Candidate]:
    """The deduped candidate list: every mesh factorization x every rule
    family, resolved against the param shapes. ``batch_divisor`` (the
    global batch size) prunes factorizations whose data-axis product
    cannot divide the batch."""
    fams = tuple(families) if families else tuple(RULE_FAMILIES)
    seen: Dict[Tuple, str] = {}
    out: List[Candidate] = []
    for mesh_axes in mesh_factorizations(ndev, axis_names):
        sizes = dict(mesh_axes)
        data_deg = 1
        for a in DATA_AXES:
            data_deg *= int(sizes.get(a, 1))
        if batch_divisor is not None and data_deg > 0 \
                and batch_divisor % data_deg != 0:
            continue
        batch_axes = tuple(a for a in DATA_AXES
                           if int(sizes.get(a, 1)) > 1)
        for fam in fams:
            rules = RULE_FAMILIES[fam]
            table = tuple(sorted(
                (name, _resolve_param(rules, name, shape, sizes, fsdp_axis))
                for name, shape in shapes.items()))
            mesh_name = ".".join(f"{a}{n}" for a, n in mesh_axes if n > 1) \
                or "single"
            cand = Candidate(name=f"{mesh_name}/{fam}", family=fam,
                             mesh_axes=tuple(mesh_axes),
                             param_specs=table, batch_axes=batch_axes)
            sig = cand.signature()
            if sig in seen:
                continue
            seen[sig] = cand.name
            out.append(cand)
    return out
