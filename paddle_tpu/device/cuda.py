"""paddle.device.cuda-compatible memory-stat API served by PJRT device stats
(fluid/memory/stats.h analog — SURVEY §5.5 "device memory via PJRT stats").
Named `cuda` for ported-code compatibility; it reports the accelerator."""

from __future__ import annotations

import jax


def _dev(device=None):
    if isinstance(device, int):
        return jax.devices()[device]
    accel = [d for d in jax.devices() if d.platform != "cpu"]
    return (accel or jax.devices())[0]


def _stats(device=None):
    d = _dev(device)
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def device_count() -> int:
    return len([d for d in jax.devices() if d.platform != "cpu"]) or len(jax.devices())


def max_memory_allocated(device=None) -> int:
    return int(_stats(device).get("peak_bytes_in_use", 0))


def max_memory_reserved(device=None) -> int:
    s = _stats(device)
    return int(s.get("peak_pool_bytes", s.get("peak_bytes_in_use", 0)))


def memory_allocated(device=None) -> int:
    return int(_stats(device).get("bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    s = _stats(device)
    return int(s.get("pool_bytes", s.get("bytes_limit", 0)))


def empty_cache():
    """No pooled host-side cache to drop; XLA owns device memory."""


def synchronize(device=None):
    jax.effects_barrier()


def get_device_properties(device=None):
    d = _dev(device)

    class _Props:
        name = d.device_kind
        total_memory = int(_stats(device).get("bytes_limit", 0))
        major = 0
        minor = 0
        multi_processor_count = getattr(d, "core_count", 1) or 1

    return _Props()


def get_device_name(device=None) -> str:
    return _dev(device).device_kind


def get_device_capability(device=None):
    return (0, 0)
