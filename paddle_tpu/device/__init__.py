"""paddle.device (python/paddle/device + device/cuda analog): device
selection and memory stats over jax/PJRT. `gpu`-named APIs are kept as
aliases onto the accelerator (TPU) so ported scripts keep working."""

from __future__ import annotations

from typing import Optional

import jax

from . import cuda  # noqa: F401

_current = None


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    """Platform-scanned custom backends plus plugin-registered ones (the two
    registration paths: jax_plugins entry points and register_custom_device)."""
    from .plugin import list_custom_devices

    scanned = [p for p in get_all_device_type() if p not in ("cpu", "gpu", "tpu")]
    return sorted(set(scanned) | set(list_custom_devices()))


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices() if d.platform not in ("cpu", "gpu", "tpu")]


def set_device(device: str):
    """'cpu' | 'tpu' | 'tpu:0' | 'gpu:0' (alias for the accelerator)."""
    global _current
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    if name == "gpu":
        name = "tpu" if any(d.platform == "tpu" for d in jax.devices()) else jax.devices()[0].platform
    matches = [d for d in jax.devices() if d.platform == name]
    if not matches:
        matches = [d for d in jax.devices()]
    _current = matches[min(idx, len(matches) - 1)]
    try:
        jax.config.update("jax_default_device", _current)
    except Exception:
        pass
    return _current


def get_device() -> str:
    d = _current or jax.devices()[0]
    platform = "gpu" if d.platform == "cuda" else d.platform
    return f"{platform}:{d.id}"


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_custom_device(device_type: Optional[str] = None) -> bool:
    # the TPU plugin IS a custom/pluggable device in PJRT terms
    return any(d.platform not in ("cpu",) for d in jax.devices())


def synchronize(device=None):
    """Block until all queued device work completes (cudaDeviceSynchronize
    analog): XLA arrays are futures, so an effects barrier is the sync."""
    jax.effects_barrier()


class Stream:
    """API-parity stub: XLA owns scheduling; there are no user streams."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        jax.effects_barrier()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def synchronize(self):
        jax.effects_barrier()


def get_cudnn_version():
    """None: no cuDNN in the TPU build (reference returns version int or None)."""
    return None


def is_compiled_with_cinn() -> bool:
    return False


from ..core.place import IPUPlace, MLUPlace, NPUPlace, XPUPlace  # noqa: F401,E402


class Stream:
    """Compat stream handle: XLA owns real streams; this tracks identity only."""

    def __init__(self, device=None, priority=2):
        self.device = device
        self.priority = priority

    def synchronize(self):
        import jax

        jax.effects_barrier()


_current_stream = Stream()


def current_stream(device=None) -> Stream:
    return _current_stream


def set_stream(stream: Stream):
    global _current_stream
    old, _current_stream = _current_stream, stream
    return old


class stream_guard:
    def __init__(self, stream: Stream):
        self.stream = stream

    def __enter__(self):
        self._old = set_stream(self.stream)
        return self.stream

    def __exit__(self, *exc):
        set_stream(self._old)
        return False


from . import plugin  # noqa: E402,F401
from .plugin import (  # noqa: E402,F401
    is_custom_device_available,
    register_custom_device,
)
