"""Custom-device plugin API: out-of-tree hardware backends.

Reference surface: phi/backends/custom/ + phi/capi — a C ABI
(C_DeviceInterface, device_ext.h:94) that out-of-tree backends implement and
Paddle dlopens. The TPU-native equivalent IS the PJRT plugin contract: a
backend ships a PJRT C-API shared library, jax loads it, and every op lowers
through StableHLO — no per-op kernel ABI needed (the compiler is the ABI).

This module is the registration surface: point it at a PJRT plugin .so and
the device becomes a jax backend usable by the whole framework.
"""

from __future__ import annotations

_registered = {}


def register_custom_device(name: str, library_path: str, options: dict = None):
    """Register an out-of-tree PJRT plugin as a named device backend.

    The analog of dropping a CustomDevice .so into the reference's plugin dir
    (phi/backends/custom/custom_device.cc load path).
    """
    try:  # jax keeps this in xla_bridge; the module path has moved across versions
        from jax._src.xla_bridge import register_plugin
    except ImportError:  # pragma: no cover - version-dependent fallback
        try:
            from jax.lib.xla_bridge import register_plugin  # older layout
        except ImportError as e:
            raise RuntimeError(
                "this jax version exposes no PJRT plugin registration hook; "
                "register the plugin via the jax_plugins entry-point mechanism instead"
            ) from e

    register_plugin(name, library_path=library_path, options=options or {})
    _registered[name] = library_path
    return name


def list_custom_devices() -> list:
    """Names of plugin-registered backends (fake/test doubles included)."""
    return sorted(_registered)


def is_custom_device_available(name: str) -> bool:
    import jax

    try:
        return len(jax.devices(name)) > 0
    except Exception:
        return False
