// Tensor — the reference goapi tensor.go analog over PT_Tensor
// (native/include/pt_extension.h): dense host tensors with the shared dtype
// codes.
package goapi

import "fmt"

// DataType mirrors the PT dtype codes (pt_extension.h / paddle_tpu.native).
type DataType int32

const (
	Float32 DataType = 0
	Float64 DataType = 1
	Float16 DataType = 2
	Bfloat16 DataType = 3
	Int8    DataType = 4
	Uint8   DataType = 5
	Int16   DataType = 6
	Int32   DataType = 7
	Int64   DataType = 8
	Bool    DataType = 9
)

// Tensor is a dense host tensor handed to / received from the predictor.
type Tensor struct {
	Dtype DataType
	Shape []int64
	// exactly one of these backs the data, by dtype
	F32 []float32
	I32 []int32
	I64 []int64
	Raw []byte
}

func numel(shape []int64) int64 {
	n := int64(1)
	for _, d := range shape {
		n *= d
	}
	return n
}

// NewTensorFloat32 builds a float32 tensor; len(data) must match the shape.
func NewTensorFloat32(shape []int64, data []float32) *Tensor {
	return &Tensor{Dtype: Float32, Shape: shape, F32: data}
}

// NewTensorInt64 builds an int64 tensor (token ids etc.).
func NewTensorInt64(shape []int64, data []int64) *Tensor {
	return &Tensor{Dtype: Int64, Shape: shape, I64: data}
}

func (t *Tensor) check() error {
	n := numel(t.Shape)
	var have int64
	switch t.Dtype {
	case Float32:
		have = int64(len(t.F32))
	case Int32:
		have = int64(len(t.I32))
	case Int64:
		have = int64(len(t.I64))
	default:
		have = int64(len(t.Raw))
		if have > 0 {
			return nil // raw bytes: caller owns the layout
		}
	}
	if have != n {
		return fmt.Errorf("tensor data length %d != shape product %d", have, n)
	}
	return nil
}
