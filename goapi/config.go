// Config — the reference goapi config.go analog (PD_Config* surface reduced
// to what the TPU serving path needs: a model prefix; GPU/TRT/MKLDNN toggles
// are accepted but inert, matching paddle_tpu.inference.Config).
package goapi

// Config holds predictor construction options.
type Config struct {
	modelPrefix string
	paramsFile  string
}

// NewConfig returns an empty Config.
func NewConfig() *Config {
	return &Config{}
}

// SetModel sets the model prefix (the path passed to paddle.jit.save) —
// reference Config.SetModel(model, params).
func (c *Config) SetModel(model string, params ...string) {
	c.modelPrefix = model
	if len(params) > 0 {
		c.paramsFile = params[0]
	}
}

// ModelDir returns the configured model prefix (reference Config.ProgFile).
func (c *Config) ModelDir() string {
	return c.modelPrefix
}

// EnableUseGpu is accepted for API parity and inert: placement is XLA's.
func (c *Config) EnableUseGpu(memoryMB uint64, deviceID int32) {}

// SwitchIrOptim is accepted for parity; the IR pipeline always runs.
func (c *Config) SwitchIrOptim(enable bool) {}
