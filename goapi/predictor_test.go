// Smoke test (reference predictor_test.go): needs a model saved by
// tests/test_goapi.py's harness; PT_MODEL points at the prefix.
package goapi

import (
	"math"
	"os"
	"testing"
)

func TestPredictorSmoke(t *testing.T) {
	prefix := os.Getenv("PT_MODEL")
	if prefix == "" {
		t.Skip("PT_MODEL not set (run via tests/test_goapi.py)")
	}
	config := NewConfig()
	config.SetModel(prefix)
	pred, err := NewPredictor(config)
	if err != nil {
		t.Fatal(err)
	}
	defer pred.Destroy()
	data := make([]float32, 3*8)
	for i := range data {
		data[i] = float32(i%7) * 0.25
	}
	outs, err := pred.Run([]*Tensor{NewTensorFloat32([]int64{3, 8}, data)})
	if err != nil {
		t.Fatal(err)
	}
	if len(outs) != 1 || len(outs[0].Shape) != 2 || outs[0].Shape[0] != 3 {
		t.Fatalf("unexpected outputs: %+v", outs)
	}
	for _, v := range outs[0].F32 {
		if math.IsNaN(float64(v)) {
			t.Fatal("NaN output")
		}
	}
}
