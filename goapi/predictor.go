// Predictor — the reference goapi predictor.go analog: cgo over the
// pt_inference.h C ABI (which embeds the XLA/PJRT serving runtime).
package goapi

/*
#include <stdint.h>
#include <stdlib.h>
#include "pt_inference.h"
*/
import "C"

import (
	"fmt"
	"runtime"
	"sync"
	"unsafe"
)

var initOnce sync.Once
var initErr error

func ensureInit() error {
	initOnce.Do(func() {
		if C.pt_infer_init() != 0 {
			initErr = fmt.Errorf("pt_infer_init: %s", C.GoString(C.pt_infer_last_error()))
		}
	})
	return initErr
}

// Predictor wraps one loaded model (reference Predictor).
type Predictor struct {
	h unsafe.Pointer
}

// NewPredictor loads the model named by config (reference NewPredictor).
func NewPredictor(config *Config) (*Predictor, error) {
	if err := ensureInit(); err != nil {
		return nil, err
	}
	cPrefix := C.CString(config.ModelDir())
	defer C.free(unsafe.Pointer(cPrefix))
	h := C.pt_predictor_create(cPrefix)
	if h == nil {
		return nil, fmt.Errorf("pt_predictor_create: %s", C.GoString(C.pt_infer_last_error()))
	}
	p := &Predictor{h: h}
	runtime.SetFinalizer(p, func(p *Predictor) { p.Destroy() })
	return p, nil
}

// Destroy releases the native handle (idempotent).
func (p *Predictor) Destroy() {
	if p.h != nil {
		C.pt_predictor_destroy(p.h)
		p.h = nil
	}
}

func fillCTensor(dst *C.PT_Tensor, t *Tensor, pinner *runtime.Pinner) error {
	if err := t.check(); err != nil {
		return err
	}
	if len(t.Shape) > int(C.PT_MAX_NDIM) {
		return fmt.Errorf("tensor rank %d exceeds PT_MAX_NDIM", len(t.Shape))
	}
	dst.dtype = C.int32_t(t.Dtype)
	dst.ndim = C.int32_t(len(t.Shape))
	for i, d := range t.Shape {
		dst.shape[i] = C.int64_t(d)
	}
	var ptr unsafe.Pointer
	switch {
	case len(t.F32) > 0:
		ptr = unsafe.Pointer(&t.F32[0])
	case len(t.I32) > 0:
		ptr = unsafe.Pointer(&t.I32[0])
	case len(t.I64) > 0:
		ptr = unsafe.Pointer(&t.I64[0])
	case len(t.Raw) > 0:
		ptr = unsafe.Pointer(&t.Raw[0])
	default:
		return fmt.Errorf("empty tensor")
	}
	// pin the Go-owned buffer so storing its pointer in C-allocated memory
	// and passing it to C is legal under the cgo pointer rules
	pinner.Pin(ptr)
	dst.data = ptr
	return nil
}

// Run executes the model on inputs and returns all outputs
// (reference Predictor.Run + output-handle copies collapsed into one call).
func (p *Predictor) Run(inputs []*Tensor) ([]*Tensor, error) {
	if p.h == nil {
		return nil, fmt.Errorf("predictor destroyed")
	}
	// the PT_Tensor array lives in C memory (a Go slice of structs holding
	// Go data pointers would trip the cgo pointer-passing checker)
	var insPtr *C.PT_Tensor
	var pinner runtime.Pinner
	defer pinner.Unpin()
	if len(inputs) > 0 {
		raw := C.malloc(C.size_t(len(inputs)) * C.size_t(unsafe.Sizeof(C.PT_Tensor{})))
		if raw == nil {
			return nil, fmt.Errorf("malloc failed")
		}
		defer C.free(raw)
		cIns := unsafe.Slice((*C.PT_Tensor)(raw), len(inputs))
		for i, t := range inputs {
			if err := fillCTensor(&cIns[i], t, &pinner); err != nil {
				return nil, err
			}
		}
		insPtr = &cIns[0]
	}
	if C.pt_predictor_run(p.h, insPtr, C.int32_t(len(inputs))) != 0 {
		return nil, fmt.Errorf("pt_predictor_run: %s", C.GoString(C.pt_infer_last_error()))
	}
	runtime.KeepAlive(inputs)
	n := int(C.pt_predictor_num_outputs(p.h))
	outs := make([]*Tensor, 0, n)
	for i := 0; i < n; i++ {
		var dt, nd C.int32_t
		var nbytes C.int64_t
		shape := make([]C.int64_t, int(C.PT_MAX_NDIM))
		if C.pt_predictor_output_meta(p.h, C.int32_t(i), &dt, &nd, &shape[0], &nbytes) != 0 {
			return nil, fmt.Errorf("output_meta(%d): %s", i, C.GoString(C.pt_infer_last_error()))
		}
		buf := make([]byte, int(nbytes))
		if nbytes > 0 {
			if C.pt_predictor_output_data(p.h, C.int32_t(i), unsafe.Pointer(&buf[0]), nbytes) != 0 {
				return nil, fmt.Errorf("output_data(%d): %s", i, C.GoString(C.pt_infer_last_error()))
			}
		}
		t := &Tensor{Dtype: DataType(dt), Raw: buf}
		for j := 0; j < int(nd); j++ {
			t.Shape = append(t.Shape, int64(shape[j]))
		}
		if t.Dtype == Float32 && len(buf) >= 4 {
			t.F32 = unsafe.Slice((*float32)(unsafe.Pointer(&buf[0])), len(buf)/4)
		}
		outs = append(outs, t)
	}
	return outs, nil
}
